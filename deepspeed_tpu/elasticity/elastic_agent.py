"""Elastic agent v2 — cross-host rendezvous + restart supervision.

Reference: ``deepspeed/elasticity/elastic_agent.py:DSElasticAgent`` [K]
(SURVEY §5.3): subclasses torch-elastic's agent — rendezvous store, worker
monitoring, restart on membership change or failure, each restart
re-initializing the process group and resuming from checkpoint.

TPU mapping: the process-group piece is ``jax.distributed.initialize``
driven by coordinator env vars; "resume at a different world size" is the
checkpoint reshard-on-load the runtime already provides (orbax restores
into whatever mesh the restarted world builds).  The agent owns:

* the CROSS-HOST rendezvous (``rendezvous.ElasticRendezvous`` over the
  TCP store — torch-elastic's TCPStore role): each round assigns
  ``(rank, world, coordinator)`` and rank 0's host coordinates
  ``jax.distributed`` for that round;
* supervision: run the worker (a subprocess for real deployments — a
  crash cannot take the agent down — or an in-process fn for embedding),
  heartbeat the store, and watch for (a) local worker failure, (b) a
  round bump by a peer, (c) stale peer heartbeats.  Any of the three
  tears the local worker down and re-rendezvouses — every surviving
  agent converges on the new membership within a heartbeat interval.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Callable, List, Optional

from ..utils.logging import debug_once, log_dist, logger
from .rendezvous import ElasticRendezvous, RendezvousClient, RendezvousServer


class WorkerSpec:
    """Reference-shaped description of the elastic worker: either a
    callable ``fn(restart_count, checkpoint_dir, *args)`` (in-process) or
    a ``cmd`` argv (subprocess — the production mode)."""

    def __init__(self, fn: Optional[Callable[..., Any]] = None,
                 args: tuple = (), cmd: Optional[List[str]] = None,
                 max_restarts: int = 3, monitor_interval: float = 0.1,
                 heartbeat_ttl: float = 5.0,
                 checkpoint_dir: Optional[str] = None,
                 restart_backoff_s: float = 1.0,
                 restart_backoff_max_s: float = 30.0,
                 scale_up_settle_s: float = 0.0):
        if (fn is None) == (cmd is None):
            raise ValueError("WorkerSpec needs exactly one of fn= or cmd=")
        self.fn = fn
        self.args = args
        self.cmd = list(cmd) if cmd else None
        self.max_restarts = int(max_restarts)
        self.monitor_interval = float(monitor_interval)
        self.heartbeat_ttl = float(heartbeat_ttl)
        self.checkpoint_dir = checkpoint_dir
        #: capped exponential backoff between FAILURE restarts
        #: (membership churn restarts stay prompt): delay =
        #: min(backoff * 2^(failures-1), backoff_max).  A worker dying
        #: instantly on startup (bad ckpt, OOM loop) must not respawn
        #: hot — it would burn the restart budget in milliseconds and
        #: hammer the rendezvous store
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_max_s = float(restart_backoff_max_s)
        #: settle window before re-rendezvousing on a JOIN-driven round
        #: bump (every previous peer still heartbeating): a flapping
        #: node that joins/leaves in a tight loop costs the gang at most
        #: one reshape per window instead of thrashing the mesh.
        #: Death-driven bumps (stale peers) stay prompt — capacity is
        #: already lost, waiting only loses more work.
        self.scale_up_settle_s = float(scale_up_settle_s)


class _RestartSignal(Exception):
    """Internal: membership changed / peer died — restart the attempt."""


class DSElasticAgent:
    """Supervise an elastic training worker across hosts.

    Without a rendezvous (``rdzv=None`` and no ``DS_RDZV_ENDPOINT``), this
    degrades to the single-host supervision loop (round-2 behavior).  With
    one, every attempt (re-)joins the current membership round first.
    """

    def __init__(self, spec: WorkerSpec, start_method: str = "inproc",
                 rdzv: Optional[ElasticRendezvous] = None,
                 node_id: Optional[str] = None):
        self.spec = spec
        self.start_method = start_method
        self.restart_count = 0   # total attempts (workers key resume off it)
        self.failure_count = 0   # only FAILURES consume max_restarts
        self.last_result: Any = None
        self.node_id = node_id or os.environ.get(
            "DS_ELASTIC_NODE_ID", f"node-{os.getpid()}")
        if rdzv is None and os.environ.get("DS_RDZV_ENDPOINT"):
            rdzv = ElasticRendezvous(
                RendezvousClient(os.environ["DS_RDZV_ENDPOINT"]),
                node_id=self.node_id,
                min_nodes=int(os.environ.get("DS_ELASTIC_MIN_NODES", "1")),
                max_nodes=int(os.environ.get("DS_ELASTIC_MAX_NODES", "64")))
        self.rdzv = rdzv
        self._round = -1
        self._rank = 0
        self._peers: List[str] = []
        #: world size of the last sealed round — a reseal at a different
        #: size is a RESHAPE, counted and annotated (origin vs target)
        self._world = 0
        #: injectable for tests (fake-clock backoff assertions)
        self._sleep: Callable[[float], None] = time.sleep
        # prefetch the resilience fault vocabulary OFF the supervision
        # path: the failure branches import it to map NODE_LEAVE_EXIT_
        # CODE, and a cold import there (orbax + friends, ~2.5s) would
        # gate the crash->round-bump latency every peer's teardown
        # clock depends on
        import threading

        threading.Thread(
            target=self._prefetch_fault_vocabulary, daemon=True,
            name="ds-agent-import-prefetch").start()

    @staticmethod
    def _prefetch_fault_vocabulary() -> None:
        try:
            from ..resilience.faults import NODE_LEAVE_EXIT_CODE  # noqa: F401
        except Exception as e:
            # the failure branches re-import and surface any real error
            debug_once("elastic/prefetch",
                       f"resilience prefetch failed ({e!r})")

    def _hb_payload(self):
        """The local watchdog's liveness summary (step index, step-time
        EWMA, progress age), folded into every rendezvous heartbeat so
        rank 0 can publish straggler-skew gauges; None when no watchdog
        is installed (payload-less heartbeats, round-2 behavior).  The
        collective ledger's ``coll_seq``/``coll_hash`` ride along
        whenever the ledger is on — with or without a watchdog — so
        rank 0 can flag a collective desync live."""
        from ..telemetry import (cap_heartbeat_payload,
                                 get_collective_ledger, get_watchdog)
        from ..telemetry.watchdog import DEFAULT_HEARTBEAT_MAX_BYTES

        wd = get_watchdog()
        if wd is not None:
            # the watchdog assembles AND caps its own payload with its
            # configured bound — never re-add fields its cap dropped
            # (that would ship past the operator's limit and bump the
            # drop counter every single beat)
            return wd.heartbeat_payload()
        led = get_collective_ledger()
        if not led.enabled:
            return None
        # ledger-only path (no watchdog installed): same schema version
        # + the documented default bound
        return cap_heartbeat_payload(dict(led.heartbeat_summary()),
                                     DEFAULT_HEARTBEAT_MAX_BYTES)

    def _heartbeat_tick(self) -> None:
        """One liveness beat: heartbeat (+watchdog/ledger payload); the
        bundle publisher answers collect requests and pushes fresh trip
        bundles; rank 0 also folds peer payloads into the straggler-skew
        gauges and runs the live collective-desync check."""
        self.rdzv.heartbeat(self._hb_payload())
        from ..telemetry.aggregator import check_desync_live, get_publisher

        pub = get_publisher()
        if pub is not None:
            try:
                pub.tick(self.rdzv.c)
            except Exception as e:
                # store hiccup / dump failure; the next tick retries
                debug_once("elastic/publisher_tick",
                           f"bundle publisher tick failed ({e!r}); "
                           f"retrying next heartbeat")
        else:
            # subprocess mode: the WORKER owns the publisher (and its
            # tick runs the clock sync + metrics push); the agent still
            # keeps its own store-clock estimate fresh so agent-side
            # spans land aligned in merged traces
            try:
                from ..telemetry import maybe_sync_clock

                maybe_sync_clock(self.rdzv.c, node_id=self.node_id)
            except Exception as e:
                debug_once("elastic/clock_sync",
                           f"agent clock sync failed ({e!r}); retrying "
                           f"next heartbeat")
        if self._rank == 0 and len(self._peers) > 1:
            try:
                self.rdzv.publish_straggler_stats(self._peers)
                check_desync_live(self.rdzv.c, self._peers)
            except Exception as e:
                # store hiccup; the next tick retries
                debug_once("elastic/straggler_stats",
                           f"straggler/desync publication failed ({e!r}); "
                           f"retrying next heartbeat")
            try:
                # the live cross-process rollup (ISSUE 13): ingest every
                # peer's published registry snapshot + step batch, feed
                # the cluster gauges, keep the merged exports fresh
                from ..telemetry import get_telemetry, rollup_tick

                rollup_tick(self.rdzv.c, self._peers,
                            out_dir=get_telemetry().output_path)
            except Exception as e:
                # store hiccup / peers not publishing yet; next tick
                debug_once("elastic/rollup_tick",
                           f"metrics rollup tick failed ({e!r}); "
                           f"retrying next heartbeat")

    def _record_stale_peers(self, stale: List[str]) -> None:
        """Satellite (ISSUE 2): stale-peer detections at the AGENT level
        (where they trigger teardown) get their own counter, distinct
        from rendezvous-level detections."""
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "elastic/agent_stale_peer_events", v=len(stale),
            help="stale peer heartbeats that triggered an agent restart")

    def _note_reshape(self, round_id: int, world: int) -> None:
        """A reseal at a DIFFERENT world size is a mesh reshape, not a
        mere restart: count it (total + direction — the agent-level
        mirror of the engine's reshard counters, so the two can be
        cross-checked against an injected chaos schedule) and annotate
        origin/target topology into the next debug bundle."""
        prev = self._world
        self._world = int(world)
        if not prev or prev == world:
            return
        direction = "shrink" if world < prev else "grow"
        from ..telemetry import get_flight_recorder, get_telemetry

        tel = get_telemetry()
        tel.inc_counter(
            "resilience/reshapes_total",
            help="snapshots restored onto a DIFFERENT mesh shape "
                 "(elastic reshard-on-restore)")
        tel.inc_counter(
            f"resilience/reshapes_{direction}_total",
            help="reshard-on-restore restores, by direction (the "
                 "{direction} breakdown of resilience/reshapes_total)")
        get_flight_recorder().annotate("reshape", {
            "direction": direction, "source": "rendezvous",
            "round": int(round_id),
            "origin": {"world_size": prev},
            "target": {"world_size": int(world),
                       "gang": list(self._peers)}})
        log_dist(f"elastic agent[{self.node_id}]: mesh RESHAPE "
                 f"({direction}): world {prev} -> {world} at round "
                 f"{round_id}")

    # -- rendezvous --------------------------------------------------------

    def _rendezvous(self) -> None:
        """(Re-)join the world.  Store-backed when available; else the
        static env the launcher set (COORDINATOR_ADDRESS / NUM_PROCESSES /
        PROCESS_ID)."""
        if self.rdzv is not None:
            r, rank, world, coord = self.rdzv.next_round()
            self._round = r
            self._rank = rank
            # monitor the FROZEN gang, not the raw members key: a node
            # squeezed out by max_nodes appended itself to members but is
            # parked as standby and never heartbeats — treating it as a
            # peer would churn the round forever
            sealed = self.rdzv.c.get(
                ElasticRendezvous._sealed_key(r)) or [[]]
            self._peers = list(sealed[0])
            os.environ["COORDINATOR_ADDRESS"] = coord
            os.environ["NUM_PROCESSES"] = str(world)
            os.environ["PROCESS_ID"] = str(rank)
            # scale-up joiner flag: the worker's resume path reads it to
            # bootstrap mid-run state from a peer replica instead of
            # starting at step 0 (cleared for ordinary members so a
            # stale export never misleads a later attempt)
            if getattr(self.rdzv, "joined_running", False):
                os.environ["DS_ELASTIC_JOINED_RUNNING"] = "1"
            else:
                os.environ.pop("DS_ELASTIC_JOINED_RUNNING", None)
            self._note_reshape(r, world)
            log_dist(f"elastic rendezvous: round={r} rank={rank}/{world} "
                     f"coordinator={coord}")
            # per-node heartbeat ages in every future debug bundle: a
            # watchdog hang dump then distinguishes "my host stalled"
            # from "a peer died" (satellite, ISSUE 2)
            from ..telemetry import get_flight_recorder

            get_flight_recorder().register_context(
                "heartbeat_ages",
                lambda: self.rdzv.peer_heartbeat_ages(self._peers))
            get_flight_recorder().annotate(
                "rendezvous", {"round": r, "rank": rank, "world": world,
                               "coordinator": coord})
        coord = os.environ.get("COORDINATOR_ADDRESS")
        if not coord or self.spec.cmd is not None:
            return  # subprocess workers init jax.distributed themselves
        import jax

        try:
            jax.distributed.shutdown()
        except Exception as e:
            # not initialized yet
            debug_once("elastic/dist_shutdown",
                       f"jax.distributed.shutdown before re-init: {e!r}")
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PROCESS_ID", "0")))

    # -- supervision loop --------------------------------------------------

    def run(self) -> Any:
        spec = self.spec
        while True:
            try:
                self._rendezvous()
                if spec.cmd is not None:
                    self.last_result = self._run_subprocess()
                else:
                    self.last_result = self._run_fn()
                if self.rdzv is not None:
                    # graceful leave: peers must not mistake a finished
                    # node's silent heartbeat for a death and tear down
                    # their own near-complete attempts
                    self.rdzv.leave()
                log_dist(f"elastic worker finished after "
                         f"{self.restart_count} restart(s)")
                return self.last_result
            except _RestartSignal as e:
                # membership changes (scale-up joins, peer death noticed
                # elsewhere, round bumps) are the elastic steady state, not
                # worker failures: they restart WITHOUT consuming the
                # max_restarts budget, so a healthy job that scales many
                # times never gives up (torch-elastic behavior)
                self._maybe_restart(e, announce=False, budgeted=False)
            except SystemExit as e:
                # scripts commonly end via sys.exit(main()); code 0/None is
                # success, anything else is a worker failure to supervise
                if e.code in (0, None):
                    return self.last_result
                self._maybe_restart(
                    RuntimeError(f"worker exited with code {e.code}"))
            except Exception as e:  # worker failure → restart or give up
                from ..resilience.faults import NodeLeaveRequested

                if isinstance(e, NodeLeaveRequested):
                    # scale-DOWN, not a crash: leave gracefully, bump so
                    # the survivors reseal at the smaller world, and
                    # EXIT the supervision loop — this host is done
                    return self._leave_gang(str(e))
                self._maybe_restart(e)

    def _run_fn(self) -> Any:
        """In-process attempt.  With a rendezvous attached, a daemon thread
        keeps heartbeating (so peers don't declare this node dead mid-
        attempt) and watches the round counter; an in-process fn cannot be
        preempted, so a round bump is honored AFTER the fn returns (the
        attempt's result is discarded and the agent re-rendezvouses —
        subprocess mode is the production path for prompt teardown)."""
        spec = self.spec
        if self.rdzv is None:
            return spec.fn(self.restart_count, spec.checkpoint_dir,
                           *spec.args)
        import threading

        stop = threading.Event()
        round_moved = threading.Event()

        def beat():
            while not stop.wait(spec.monitor_interval):
                try:
                    self._heartbeat_tick()
                    if self.rdzv.current_round() != self._round:
                        # the attempt is already doomed; latch and stop so
                        # we never bump a round someone else already moved
                        round_moved.set()
                        return
                    stale = self.rdzv.stale_peers(self._peers,
                                                  spec.heartbeat_ttl)
                    if stale:
                        # bump ONCE, then latch — re-bumping every tick
                        # would storm the counter past the round peers
                        # are trying to re-form on
                        self._record_stale_peers(stale)
                        self.rdzv.bump_round(f"stale peers {stale}")
                        round_moved.set()
                        return
                except ConnectionError as e:
                    # control plane degraded (the store is down or this
                    # node is partitioned): heartbeats are journaled so
                    # they buffer and replay on reconnect — keep beating;
                    # the client counts the outage
                    # (elasticity/store_reconnects_total + degraded
                    # seconds) when it heals
                    debug_once("elastic/heartbeat_degraded",
                               f"store unreachable in the beat thread "
                               f"({e!r}); heartbeats buffered, resuming "
                               f"on reconnect")
                except Exception as e:
                    # store hiccup — keep the attempt running
                    debug_once("elastic/heartbeat_beat",
                               f"worker heartbeat failed ({e!r}); "
                               f"retrying next interval")

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            result = spec.fn(self.restart_count, spec.checkpoint_dir,
                             *spec.args)
        finally:
            stop.set()
            t.join(timeout=2)
        if round_moved.is_set():
            raise _RestartSignal(
                f"membership round moved past {self._round} during the "
                f"attempt — result discarded, re-rendezvousing")
        return result

    def _run_subprocess(self) -> int:
        """Spawn the worker argv and monitor it: heartbeat, watch the
        round counter and peer heartbeats, reap the child.  Returns the
        child's exit code (0) on success."""
        spec = self.spec
        env = dict(os.environ)
        env["DS_ELASTIC_RESTART_COUNT"] = str(self.restart_count)
        # the worker must present the SAME node id the agent sealed into
        # the ring: the resilience tier-2 buddy lookup and the bundle
        # publisher both key their store slots on it
        env["DS_ELASTIC_NODE_ID"] = self.node_id
        # lets the node_leave fault signal a GRACEFUL leave via the
        # well-known exit code instead of an uncatchable raised
        # exception (which would read as a budgeted crash)
        env["DS_ELASTIC_SUBPROCESS"] = "1"
        if spec.checkpoint_dir:
            env["DS_ELASTIC_CHECKPOINT_DIR"] = spec.checkpoint_dir
        proc = subprocess.Popen(spec.cmd, env=env)
        try:
            while True:
                rc = proc.poll()
                if rc is not None:
                    if rc == 0:
                        return 0
                    from ..resilience.faults import (NODE_LEAVE_EXIT_CODE,
                                                     NodeLeaveRequested)

                    if rc == NODE_LEAVE_EXIT_CODE:
                        # scale-down, not a crash: run() maps this to
                        # _leave_gang (graceful leave + bump + exit)
                        raise NodeLeaveRequested(
                            f"worker exited with the node-leave code "
                            f"({rc})")
                    if self.rdzv is not None:
                        self.rdzv.bump_round(
                            f"worker on {self.node_id} exited rc={rc}")
                    raise RuntimeError(
                        f"worker exited with code {rc}")
                if self.rdzv is not None:
                    try:
                        self._heartbeat_tick()
                        moved = self.rdzv.current_round() != self._round
                        stale = self.rdzv.stale_peers(self._peers,
                                                      spec.heartbeat_ttl)
                    except (OSError, ConnectionError):
                        # transient store hiccup must not kill a healthy
                        # worker (matches the in-process beat thread)
                        moved, stale = False, []
                    if moved:
                        raise _RestartSignal(
                            f"membership round moved past {self._round}")
                    if stale:
                        self._record_stale_peers(stale)
                        self.rdzv.bump_round(f"stale peers {stale}")
                        raise _RestartSignal(f"peers {stale} went silent")
                time.sleep(spec.monitor_interval)
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()

    def _leave_gang(self, reason: str) -> Any:
        """Graceful scale-down exit: mark left (peers must not mistake
        our silence for a death), bump the round so the survivors reseal
        at the smaller world NOW (instead of after a heartbeat-ttl
        grace), and return the last result."""
        if self.rdzv is not None:
            try:
                self.rdzv.leave()
                self.rdzv.bump_round(
                    f"node {self.node_id} leaving (scale-down): {reason}")
            except Exception as e:
                # the peers' ttl-based stale detection still reseals;
                # leaving must not crash the leaver
                debug_once("elastic/leave",
                           f"graceful leave failed ({e!r}); peers will "
                           f"notice via heartbeat ttl")
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "elastic/node_leaves_total",
            help="nodes that left the gang gracefully (scale-down)")
        log_dist(f"elastic agent[{self.node_id}]: left the gang "
                 f"({reason}) after {self.restart_count} restart(s)")
        return self.last_result

    def _maybe_restart(self, e: BaseException, announce: bool = True,
                       budgeted: bool = True) -> None:
        spec = self.spec
        self.restart_count += 1
        delay = spec.monitor_interval
        if not budgeted and spec.scale_up_settle_s > 0:
            # membership-churn restart: when every previous peer is
            # still heartbeating AND none left gracefully, the bump was
            # JOIN-driven — wait the settle window so a flapping node
            # costs one reshape per window, not one per flap.  A
            # capacity-LOSS bump (stale peers, or a graceful leaver —
            # who never goes stale because stale_peers skips left
            # nodes) keeps the prompt monitor_interval delay.
            try:
                stale = (self.rdzv.stale_peers(self._peers,
                                               spec.heartbeat_ttl)
                         if self.rdzv is not None else [])
                stale = stale or (self.rdzv.left_peers(self._peers)
                                  if self.rdzv is not None else [])
            except (OSError, ConnectionError):
                stale = []  # store hiccup — don't stall the re-form
            if self.rdzv is not None and not stale:
                delay = max(delay, spec.scale_up_settle_s)
                from ..telemetry import get_telemetry

                get_telemetry().inc_counter(
                    "elastic/scale_up_settles_total",
                    help="join-driven round bumps held for the "
                         "scale-up settle window")
        if budgeted:
            self.failure_count += 1
            if self.failure_count > spec.max_restarts:
                logger.error(f"elastic agent: giving up after "
                             f"{spec.max_restarts} failures ({e!r})")
                raise e
            # capped exponential backoff between FAILURE restarts: a
            # crash-looping worker must not respawn hot (membership-churn
            # restarts keep the prompt monitor_interval delay — peers are
            # actively waiting in the new round)
            delay = min(
                spec.restart_backoff_s * (2 ** (self.failure_count - 1)),
                spec.restart_backoff_max_s)
        from ..telemetry import get_telemetry

        get_telemetry().inc_counter(
            "elastic/worker_restarts_total",
            help="elastic worker restarts (membership churn + failures)")
        if budgeted:
            get_telemetry().inc_counter(
                "elastic/worker_failure_restarts_total",
                help="elastic worker restarts that consumed the failure "
                     "budget")
        level = logger.warning if announce else logger.info
        level(f"elastic agent[{self.node_id}]: restarting (attempt "
              f"{self.restart_count}, failures "
              f"{self.failure_count}/{spec.max_restarts}, backoff "
              f"{delay:.2f}s): {e!r}")
        self._sleep(delay)


def launch_elastic(fn: Callable[..., Any], args: tuple = (),
                   max_restarts: int = 3,
                   checkpoint_dir: Optional[str] = None) -> Any:
    """Convenience wrapper (reference ``ds_elastic`` entry role)."""
    spec = WorkerSpec(fn, args=args, max_restarts=max_restarts,
                      checkpoint_dir=checkpoint_dir)
    return DSElasticAgent(spec).run()


def cli_main(argv=None) -> int:
    """``ds_elastic`` CLI: supervise a user script under the agent.

    ``--rdzv_endpoint host:port`` joins a cross-host rendezvous store
    (start one with ``--standalone`` on the first node); without it the
    agent is the single-host supervision loop."""
    import argparse
    import runpy

    parser = argparse.ArgumentParser(prog="ds_elastic")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--checkpoint_dir", default=None)
    parser.add_argument("--rdzv_endpoint", default=None,
                        help="host:port of the rendezvous store")
    parser.add_argument("--standalone", action="store_true",
                        help="also host the rendezvous store here")
    parser.add_argument("--min_nodes", type=int, default=1)
    parser.add_argument("--max_nodes", type=int, default=64)
    parser.add_argument("--node_id", default=None)
    parser.add_argument("--scale_up_settle", type=float, default=0.0,
                        help="settle window (s) before re-rendezvousing "
                             "on a JOIN-driven round bump — a flapping "
                             "node costs one reshape per window instead "
                             "of thrashing the mesh")
    parser.add_argument("--subprocess", action="store_true",
                        help="run the script as a supervised subprocess "
                             "(recommended with a rendezvous)")
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs="*")
    args = parser.parse_args(argv)

    server = None
    if args.standalone:
        host = (args.rdzv_endpoint or "127.0.0.1:29499").rsplit(":", 1)
        server = RendezvousServer(host[0], int(host[1]))
        os.environ["DS_RDZV_ENDPOINT"] = server.endpoint
        print(f"rendezvous store: {server.endpoint}")
    elif args.rdzv_endpoint:
        os.environ["DS_RDZV_ENDPOINT"] = args.rdzv_endpoint
    os.environ["DS_ELASTIC_MIN_NODES"] = str(args.min_nodes)
    os.environ["DS_ELASTIC_MAX_NODES"] = str(args.max_nodes)
    if args.node_id:
        os.environ["DS_ELASTIC_NODE_ID"] = args.node_id

    try:
        if args.subprocess or os.environ.get("DS_RDZV_ENDPOINT"):
            spec = WorkerSpec(
                cmd=[sys.executable, args.user_script] + list(args.user_args),
                max_restarts=args.max_restarts,
                checkpoint_dir=args.checkpoint_dir,
                scale_up_settle_s=args.scale_up_settle)
            DSElasticAgent(spec).run()
            return 0

        def worker(restart_count, ckpt_dir):
            os.environ["DS_ELASTIC_RESTART_COUNT"] = str(restart_count)
            if ckpt_dir:
                os.environ["DS_ELASTIC_CHECKPOINT_DIR"] = ckpt_dir
            sys.argv = [args.user_script] + list(args.user_args)
            runpy.run_path(args.user_script, run_name="__main__")
            return 0

        launch_elastic(worker, max_restarts=args.max_restarts,
                       checkpoint_dir=args.checkpoint_dir)
        return 0
    finally:
        if server is not None:
            server.shutdown()
