from .elasticity import compute_elastic_config, get_compatible_gpus
from .rendezvous import (ElasticRendezvous, RendezvousClient,
                         RendezvousServer, StoreUnavailableError,
                         control_plane_status, partition_all)

__all__ = ["compute_elastic_config", "get_compatible_gpus",
           "ElasticRendezvous", "RendezvousClient", "RendezvousServer",
           "StoreUnavailableError", "control_plane_status",
           "partition_all"]
