from .elasticity import compute_elastic_config, get_compatible_gpus

__all__ = ["compute_elastic_config", "get_compatible_gpus"]
