"""Hygiene lints — exception-handling discipline.

One rule, ``bare-except``: a bare ``except:`` anywhere, or an
``except Exception/BaseException:`` whose body does NOTHING (only
``pass``/``continue``).  Silent swallows are how the repo once hid real
backend breakage for two rounds (the ``effects_barrier`` case now
documented in comm.py) — a broad handler is fine as a last-resort
fallback, but it must either narrow the type or say what it ate, once,
with context.  Handlers that log, re-raise, set a fallback value, or
return are not flagged: those made a decision; ``pass`` made none.
"""

from __future__ import annotations

import ast
from typing import List

from .core import AnalysisConfig, Finding, Rule, SourceModule, register

_BROAD = ("Exception", "BaseException")


def _check_bare_except(mods: List[SourceModule],
                       cfg: AnalysisConfig) -> List[Finding]:
    out: List[Finding] = []
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                out.append(mod.finding(
                    "bare-except", node,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "too — name the exception type, or at minimum "
                    "`except Exception` with a logged reason"))
                continue
            if not (isinstance(node.type, ast.Name)
                    and node.type.id in _BROAD):
                continue
            silent = all(isinstance(stmt, (ast.Pass, ast.Continue))
                         for stmt in node.body)
            if silent:
                out.append(mod.finding(
                    "bare-except", node,
                    f"`except {node.type.id}: pass` swallows every "
                    f"failure silently — narrow the exception type, or "
                    f"log once with context (utils.logging.debug_once) "
                    f"so breakage is visible the first time it happens"))
    return out


register(Rule(
    id="bare-except", family="lint",
    summary="bare `except:` and silent `except Exception: pass` blocks",
    explain=(
        "A broad handler that does nothing converts every future bug in "
        "the protected block into silence — the repo's comms logger once "
        "hid a broken jax.effects_barrier behind exactly this shape for "
        "two rounds.  The rule flags (1) bare `except:` (which also eats "
        "SystemExit and KeyboardInterrupt) and (2) `except Exception:` / "
        "`except BaseException:` whose body is only pass/continue.  A "
        "handler that narrows the type, logs (see "
        "utils.logging.debug_once for the log-once-with-context idiom), "
        "re-raises, returns, or assigns a fallback is deliberate and "
        "passes.  Best-effort telemetry paths where even a log line is "
        "wrong belong behind an inline "
        "`# dslint: disable=bare-except` with a justification comment."),
    check=_check_bare_except))
