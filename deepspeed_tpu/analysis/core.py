"""dslint core — findings, rule registry, suppressions, config.

The static-analysis plane's spine.  Everything here is deliberately
AST-only and import-free: rules never import the modules they inspect
(importing ``runtime/engine.py`` would drag jax/XLA into a lint run and
make CI linting as heavy as a test shard).  The trade-off is that all
resolution (what does ``self._jit`` mean? is ``lax`` ``jax.lax``?) is
name-based heuristics — which is exactly why findings are gated through
a reviewed baseline instead of hard-failing on first sight.

Layers:

* :class:`Finding` — one report, keyed for baseline matching by
  ``(rule, path, symbol, message)`` (NOT line number: lines drift on
  every unrelated edit, symbols and messages don't).
* :class:`SourceModule` — parsed file + enclosing-qualname index +
  suppression table (``# dslint: disable=<rule>[,<rule>]`` trailing a
  line, ``# dslint: disable-file=<rule>`` anywhere).
* :class:`Rule` / :func:`register` — the registry the CLI and tests
  enumerate; each rule declares a family (``lint`` or ``races``) so
  ``analysis lint`` and ``analysis races`` run disjoint sets.
* :class:`AnalysisConfig` — the ``[tool.dslint]`` stanza of
  pyproject.toml (rule enable/disable, hot-path roots, lock-name
  conventions) parsed with a self-contained mini-TOML reader because
  this container's Python 3.10 predates ``tomllib``.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    """One analyzer report, anchored for baseline matching."""

    rule: str
    path: str        # repo-relative, forward slashes
    line: int
    symbol: str      # enclosing qualname ("" at module level)
    message: str

    def key(self) -> Tuple[str, str, str, str]:
        """Baseline identity — everything except the (drifting) line."""
        return (self.rule, self.path, self.symbol, self.message)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym}: {self.message}"


# ---------------------------------------------------------------------------
# parsed source + suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*dslint:\s*(disable|disable-file)\s*=\s*([\w\-, ]+)")


class SourceModule:
    """One parsed file: AST, per-node qualnames, suppression table."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._qual: Dict[int, str] = {}       # id(node) -> qualname
        self._index_qualnames(self.tree, [])
        #: line -> set of rule ids disabled on that line
        self.line_disable: Dict[int, set] = {}
        self.file_disable: set = set()
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self.file_disable |= rules
            else:
                self.line_disable.setdefault(i, set()).update(rules)

    def _index_qualnames(self, node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = stack + [child.name]
                self._qual[id(child)] = ".".join(qual)
                self._index_qualnames(child, qual)
            else:
                if stack:
                    self._qual[id(child)] = ".".join(stack)
                self._index_qualnames(child, stack)

    def qualname(self, node: ast.AST) -> str:
        return self._qual.get(id(node), "")

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disable:
            return True
        return rule in self.line_disable.get(line, set())

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.rel,
                       line=getattr(node, "lineno", 0),
                       symbol=self.qualname(node), message=message)


def iter_modules(root: str, paths: Iterable[str]) -> List[SourceModule]:
    """Parse every ``*.py`` under ``paths`` (files or dirs, relative to
    ``root``).  Unparseable files are skipped — a syntax error is the
    interpreter's job to report, not the linter's.  A path that does not
    exist raises: a typo'd root silently reporting "clean" would turn a
    CI gate into a no-op."""
    mods: List[SourceModule] = []
    seen = set()
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            raise FileNotFoundError(f"analysis path does not exist: {full}")
        if os.path.isfile(full):
            files = [full]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        for f in sorted(files):
            f = os.path.abspath(f)
            if f in seen:
                continue
            seen.add(f)
            rel = os.path.relpath(f, root)
            try:
                with open(f, "r") as fh:
                    text = fh.read()
                mods.append(SourceModule(f, rel, text))
            except (OSError, SyntaxError, ValueError):
                continue
    return mods


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Rule:
    id: str
    family: str        # "lint" | "races"
    summary: str       # one line, for `explain` listings
    explain: str       # full intent doc, for `explain <rule>`
    check: Callable[[List[SourceModule], "AnalysisConfig"], List[Finding]]


RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    RULES[rule.id] = rule
    return rule


def _load_all_rules() -> None:
    # import for registration side effects; idempotent
    from . import hygiene, jax_rules, races  # noqa: F401


def active_rules(cfg: "AnalysisConfig", family: str) -> List[Rule]:
    _load_all_rules()
    out = []
    for rule in RULES.values():
        if rule.family != family:
            continue
        if cfg.enable and rule.id not in cfg.enable:
            continue
        if rule.id in cfg.disable:
            continue
        out.append(rule)
    return sorted(out, key=lambda r: r.id)


def run_rules(cfg: "AnalysisConfig", root: str, family: str,
              paths: Optional[List[str]] = None) -> List[Finding]:
    """Run one family's rules over the configured (or given) paths and
    filter through suppression comments.  Baseline gating is the
    caller's job (:mod:`.baseline`)."""
    mods = iter_modules(root, paths or cfg.paths)
    by_rel = {m.rel: m for m in mods}
    findings: List[Finding] = []
    for rule in active_rules(cfg, family):
        for f in rule.check(mods, cfg):
            mod = by_rel.get(f.path)
            if mod is not None and mod.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


# ---------------------------------------------------------------------------
# config — the [tool.dslint] pyproject stanza
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AnalysisConfig:
    """Everything operators may tune without touching analyzer code."""

    #: roots the linter walks by default
    paths: List[str] = dataclasses.field(
        default_factory=lambda: ["deepspeed_tpu"])
    #: when non-empty, ONLY these rules run
    enable: List[str] = dataclasses.field(default_factory=list)
    disable: List[str] = dataclasses.field(default_factory=list)
    #: checked-in findings baseline (repo-relative)
    baseline: str = ".dslint-baseline.json"
    #: dirs where every jax.jit must ride the compile tracker
    jit_roots: List[str] = dataclasses.field(
        default_factory=lambda: ["deepspeed_tpu/runtime",
                                 "deepspeed_tpu/inference"])
    #: wrapper names that ARE the tracked path
    tracked_jit_names: List[str] = dataclasses.field(
        default_factory=lambda: ["tracked_jit", "_jit"])
    #: the one package allowed to touch jax.lax collectives directly
    collective_home: str = "deepspeed_tpu/comm"
    #: hot-path entry points, "relative/path.py::Qual.name"
    hot_path_roots: List[str] = dataclasses.field(
        default_factory=lambda: [
            "deepspeed_tpu/runtime/engine.py::DeepSpeedEngine.train_step"])
    #: functions/methods the host-sync rule neither scans nor descends
    #: into (the deliberate telemetry fences + diagnostics surfaces)
    host_sync_allow: List[str] = dataclasses.field(
        default_factory=lambda: [
            "DeepSpeedEngine._record_step_telemetry",
            "RecoveryPolicy.observe_step",
        ])
    #: parameter-name globs the static-argnums hazard treats as
    #: array-valued
    array_param_names: List[str] = dataclasses.field(
        default_factory=lambda: ["param*", "state*", "batch*", "grad*",
                                 "tensor*", "arr*", "*tree*", "pool*",
                                 "cache*"])
    #: attribute-name globs that count as "the class's declared lock"
    lock_name_patterns: List[str] = dataclasses.field(
        default_factory=lambda: ["*lock*", "*_mu", "*mutex*", "*cond*"])
    #: extra thread entry points the AST can't see (callback indirection),
    #: "relative/path.py::Qual.name"
    thread_roots: List[str] = dataclasses.field(
        default_factory=lambda: [
            "deepspeed_tpu/telemetry/watchdog.py::HangWatchdog._loop",
            "deepspeed_tpu/telemetry/aggregator.py::BundlePublisher.tick",
            "deepspeed_tpu/resilience/snapshot.py::SnapshotManager._flush_sync",
        ])
    #: attribute-name globs the races audit never reports (counters whose
    #: worst case is a benign off-by-one in diagnostics output)
    races_ignore_attrs: List[str] = dataclasses.field(default_factory=list)

    def lock_like(self, attr: str) -> bool:
        return any(fnmatch.fnmatch(attr, pat)
                   for pat in self.lock_name_patterns)

    def arrayish(self, name: str) -> bool:
        return any(fnmatch.fnmatch(name, pat)
                   for pat in self.array_param_names)


def _strip_toml_comment(line: str) -> str:
    """Cut an inline ``#`` comment — but only OUTSIDE quoted strings
    (paths legitimately contain ``#``-free but quote-sensitive text;
    a comment swallowed into a joined multi-line list would silently
    drop the whole key)."""
    quote: Optional[str] = None
    for i, ch in enumerate(line):
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def _parse_toml_section(text: str, section: str) -> Dict[str, Any]:
    """Mini-TOML: just enough for our own stanza (string/bool/int
    scalars and string lists, single- or multi-line, inline comments).
    Python 3.10 has no tomllib and the container must not grow
    dependencies."""
    lines = text.splitlines()
    in_section = False
    buf: List[str] = []
    logical: List[str] = []
    depth = 0
    for raw in lines:
        line = _strip_toml_comment(raw).strip()
        if line.startswith("["):
            if in_section and depth == 0:
                break
            in_section = line == f"[{section}]"
            continue
        if not in_section or not line:
            continue
        buf.append(line)
        depth += line.count("[") - line.count("]")
        if depth <= 0:
            logical.append(" ".join(buf))
            buf, depth = [], 0
    out: Dict[str, Any] = {}
    for entry in logical:
        if "=" not in entry:
            continue
        key, _, value = entry.partition("=")
        value = value.strip()
        # only a bare scalar bool is rewritten — a blanket regex would
        # corrupt string values that happen to contain true/false
        if value in ("true", "false"):
            value = value.capitalize()
        try:
            out[key.strip()] = ast.literal_eval(value)
        except (ValueError, SyntaxError):
            continue
    return out


def load_config(root: str) -> AnalysisConfig:
    cfg = AnalysisConfig()
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.isfile(pyproject):
        return cfg
    with open(pyproject, "r") as fh:
        data = _parse_toml_section(fh.read(), "tool.dslint")
    for field in dataclasses.fields(AnalysisConfig):
        if field.name in data:
            setattr(cfg, field.name, data[field.name])
    return cfg


def find_repo_root(start: Optional[str] = None) -> str:
    """Nearest ancestor with a pyproject.toml (falls back to cwd)."""
    cur = os.path.abspath(start or os.getcwd())
    probe = cur
    while True:
        if os.path.isfile(os.path.join(probe, "pyproject.toml")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return cur
        probe = parent


# ---------------------------------------------------------------------------
# shared AST helpers (used by several rule modules)
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for an Attribute/Name chain; None when the chain
    bottoms out in anything but a Name (a call result, a subscript)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def parse_root_spec(spec: str) -> Tuple[str, str]:
    """Split a "relative/path.py::Qual.name" config entry."""
    path, _, qual = spec.partition("::")
    return path.replace(os.sep, "/"), qual
