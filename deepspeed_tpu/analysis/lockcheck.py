"""Instrumented-lock shim — lock-order inversion caught at test time.

The static audit (:mod:`.races`) sees missing locks; it cannot see the
dual failure, *deadlock by inconsistent acquisition order* (thread A
takes L1→L2, thread B takes L2→L1 — each waits on the other under
load, never in the fast unit test).  This shim catches the ORDER, which
is visible on every single-threaded pass through the code:

* :class:`LockOrderMonitor` keeps a process-wide directed graph of
  observed acquisition edges (holding A while acquiring B ⇒ edge A→B).
  An acquisition that would close a cycle raises
  :class:`LockOrderInversion` immediately — no actual deadlock needed.
* :class:`InstrumentedLock` wraps ``threading.Lock``/``RLock`` and
  reports to a monitor.
* :func:`instrument_locks` is the test harness entry: a context manager
  that monkeypatches ``threading.Lock``/``RLock`` so every lock built
  inside it (watchdog, metrics registry, flight recorder...) is
  instrumented, named by its creation site.

Test-time only by design: the bookkeeping is a dict hit per acquire —
fine for tests, not for the hot path.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple


class LockOrderInversion(AssertionError):
    """Two locks were taken in both orders — a latent deadlock."""


#: the real primitives, captured at import — InstrumentedLock must keep
#: working while instrument_locks() has the module-level names patched
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class LockOrderMonitor:
    """Process-wide acquisition-order graph over instrumented locks."""

    def __init__(self) -> None:
        self._graph_mu = _REAL_LOCK()
        #: edge (a, b): some thread held a while acquiring b
        self._edges: Dict[str, Set[str]] = {}
        #: first stack that created each edge, for the error message
        self._witness: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()

    # -- per-thread held stack --------------------------------------------

    def _stack(self) -> List[str]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def acquired(self, name: str) -> None:
        stack = self._stack()
        # RLock re-entry (the lock is ANYWHERE in the held stack, not
        # just on top) can never block — no ordering edge
        if stack and name not in stack:
            self._add_edge(stack[-1], name)
        stack.append(name)

    def released(self, name: str) -> None:
        stack = self._stack()
        # release may be out of LIFO order (rare but legal) — drop the
        # newest matching entry
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- the graph ---------------------------------------------------------

    def _add_edge(self, a: str, b: str) -> None:
        with self._graph_mu:
            if b in self._edges.setdefault(a, set()):
                return
            path = self._find_path(b, a)
            if path is not None:
                chain = " -> ".join(path + [b])
                prior = self._witness.get((path[0], path[1])) if \
                    len(path) > 1 else None
                raise LockOrderInversion(
                    f"lock-order inversion: acquiring '{b}' while "
                    f"holding '{a}', but the reverse order "
                    f"{chain} was already observed"
                    + (f"\nfirst observed at:\n{prior}" if prior else ""))
            self._edges[a].add(b)
            self._witness[(a, b)] = "".join(
                traceback.format_stack(limit=8)[:-2])

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src→dst in the edge graph (caller holds _graph_mu)."""
        seen = {src}
        stack: List[List[str]] = [[src]]
        while stack:
            path = stack.pop()
            node = path[-1]
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(path + [nxt])
        return None

    def edges(self) -> Dict[str, Set[str]]:
        with self._graph_mu:
            return {k: set(v) for k, v in self._edges.items()}


class InstrumentedLock:
    """A Lock/RLock that reports acquisition order to a monitor.

    Duck-types the threading lock surface the repo uses (acquire/
    release/context manager/locked).  ``name`` must be UNIQUE per lock
    object: the monitor distinguishes RLock re-entry from a second lock
    by name, so two locks sharing one name would alias in the graph and
    hide inter-instance inversions (:func:`instrument_locks` guarantees
    uniqueness with a per-lock counter)."""

    def __init__(self, monitor: LockOrderMonitor, name: str,
                 reentrant: bool = False):
        self._inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        self._monitor = monitor
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # order is checked BEFORE blocking: the inversion must surface
        # even when this run wins the race that would deadlock another
        self._monitor.acquired(self.name)
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            self._monitor.released(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._monitor.released(self.name)

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return inner_locked() if inner_locked is not None else False


@contextlib.contextmanager
def instrument_locks(monitor: Optional[LockOrderMonitor] = None):
    """Swap ``threading.Lock``/``RLock`` for instrumented ones, named by
    creation site (``file:line``).  Yields the monitor so the test can
    assert on :meth:`LockOrderMonitor.edges` — an inversion raises
    :class:`LockOrderInversion` from the acquiring thread the moment the
    cycle would close.

    Restores the real constructors on exit; locks created inside keep
    working (they wrap real primitives)."""
    mon = monitor or LockOrderMonitor()
    real_lock, real_rlock = _REAL_LOCK, _REAL_RLOCK
    counter = itertools.count()

    def _site() -> str:
        for frame in reversed(traceback.extract_stack(limit=12)[:-2]):
            if __file__ not in frame.filename \
                    and "threading" not in frame.filename:
                return f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
        return "unknown"

    # the #N suffix keeps names unique across instances created at ONE
    # site (`self._lock = threading.Lock()` in __init__): without it,
    # holding inst1's lock while taking inst2's would read as re-entry
    # and the classic inter-instance A->B/B->A deadlock would be
    # invisible to the graph
    def make_lock():
        return InstrumentedLock(mon, f"Lock@{_site()}#{next(counter)}")

    def make_rlock():
        return InstrumentedLock(mon, f"RLock@{_site()}#{next(counter)}",
                                reentrant=True)

    threading.Lock, threading.RLock = make_lock, make_rlock
    try:
        yield mon
    finally:
        threading.Lock, threading.RLock = real_lock, real_rlock
