"""dslint — the JAX/TPU-aware static-analysis plane (ISSUE 6).

PRs 1-5 detect this stack's recurring failure classes at RUNTIME
(recompile storms, desynced collectives, watchdog/publisher/snapshot
races); this package recognizes the same hazard classes in SOURCE, at
review time:

* :mod:`.jax_rules` — untracked jit sites, recompile hazards,
  host-sync-in-hot-path, donated-buffer reuse, raw collectives outside
  ``comm/``.
* :mod:`.hygiene` — bare/silent ``except`` discipline.
* :mod:`.races` — thread-safety audit over classes reachable from
  thread entry points.
* :mod:`.lockcheck` — test-time instrumented locks that fail on
  lock-order inversion.
* :mod:`.baseline` — the reviewed true-but-deferred ledger the CLI
  gates against (exit 3 on anything new).

CLI: ``python -m deepspeed_tpu.analysis {lint,races,baseline,explain}``;
config: the ``[tool.dslint]`` stanza in pyproject.toml; suppression:
``# dslint: disable=<rule>`` (line) / ``# dslint: disable-file=<rule>``.

Import-light on purpose: the analyzers never import the code they
inspect (no jax at lint time), so the CI gate is cheap.
"""

from .baseline import load_baseline, partition, write_baseline
from .core import (RULES, AnalysisConfig, Finding, Rule, SourceModule,
                   find_repo_root, iter_modules, load_config, run_rules)
from .lockcheck import (InstrumentedLock, LockOrderInversion,
                        LockOrderMonitor, instrument_locks)

__all__ = [
    "AnalysisConfig", "Finding", "Rule", "RULES", "SourceModule",
    "find_repo_root", "iter_modules", "load_config", "run_rules",
    "load_baseline", "partition", "write_baseline",
    "InstrumentedLock", "LockOrderInversion", "LockOrderMonitor",
    "instrument_locks",
]
