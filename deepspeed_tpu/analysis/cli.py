"""Operator CLI — ``python -m deepspeed_tpu.analysis {lint,races,
baseline,explain}``.

Exit codes (shared with the telemetry/resilience CLIs' convention):

* ``0`` — clean (every finding is baselined or suppressed)
* ``2`` — usage error (unknown rule, unreadable root)
* ``3`` — findings not in the baseline (the CI-gate signal)

``lint`` runs the JAX/TPU + hygiene rules; ``races`` runs the
thread-safety audit; both gate against the same baseline file, so a
single ``baseline`` run captures the full reviewed-debt ledger.
``explain <rule>`` prints the intent doc — the text a reviewer reads
before deciding fix vs suppress vs baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .core import (RULES, AnalysisConfig, _load_all_rules, active_rules,
                   find_repo_root, load_config, run_rules)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis",
        description="dslint — JAX/TPU-aware static analysis")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("paths", nargs="*",
                        help="files/dirs to analyze (default: config "
                             "paths)")
        sp.add_argument("--root", default=None,
                        help="repo root (default: nearest pyproject.toml)")
        sp.add_argument("--baseline", default=None,
                        help="baseline file (default: config)")
        sp.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignore the baseline")
        sp.add_argument("--format", choices=("text", "json"),
                        default="text")

    common(sub.add_parser(
        "lint", help="JAX/TPU correctness + hygiene rules"))
    common(sub.add_parser(
        "races", help="thread-safety audit (shared attrs off the lock)"))
    common(sub.add_parser(
        "baseline", help="regenerate the findings baseline (lint+races)"))
    exp = sub.add_parser("explain", help="print a rule's intent doc")
    exp.add_argument("rule", nargs="?", default=None,
                     help="rule id (omit to list all rules)")
    return p


def _scope_tuple(paths, root: str):
    """Repo-relative prefixes for a path-scoped run, resolved exactly as
    ``iter_modules`` resolves them (non-absolute paths join onto root,
    NOT onto cwd) — the staleness and carry-over checks must agree with
    the scan about what was observed."""
    import os

    return tuple(
        os.path.relpath(p if os.path.isabs(p) else os.path.join(root, p),
                        root).replace(os.sep, "/").rstrip("/")
        for p in paths)


def _in_scope(rel_path: str, scope) -> bool:
    return any(rel_path == s or rel_path.startswith(s + "/")
               for s in scope)


def _gate(findings, cfg: AnalysisConfig, root: str, args,
          family: str) -> int:
    import os

    bl_path = args.baseline or os.path.join(root, cfg.baseline)
    if args.no_baseline:
        new, known, stale = findings, [], []
    else:
        bl = baseline_mod.load_baseline(bl_path)
        ran = {r.id for r in active_rules(cfg, family)}
        new, known, stale = baseline_mod.partition(findings, bl, ran)
        if args.paths:
            # a path-scoped run saw only a slice of the tree — entries
            # outside it are unobserved, not stale
            scope = _scope_tuple(args.paths, root)
            stale = [e for e in stale if _in_scope(e["path"], scope)]
    if args.format == "json":
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in known],
            "stale_baseline_entries": stale}, indent=1))
    else:
        for f in new:
            print(f.render())
        if known:
            print(f"-- {len(known)} baselined finding(s) tolerated "
                  f"({bl_path})")
        if stale:
            print(f"-- note: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} no longer "
                  f"match anything — rerun `baseline` to drop them")
        if new:
            print(f"== {len(new)} NEW finding(s) — fix, suppress with "
                  f"`# dslint: disable=<rule>`, or (true-but-deferred "
                  f"only) re-baseline")
        else:
            print("== clean")
    return 3 if new else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.cmd == "explain":
        _load_all_rules()
        if args.rule is None:
            for rule in sorted(RULES.values(), key=lambda r: r.id):
                print(f"{rule.id:22s} [{rule.family}] {rule.summary}")
            return 0
        rule = RULES.get(args.rule)
        if rule is None:
            print(f"unknown rule {args.rule!r}; known: "
                  f"{', '.join(sorted(RULES))}", file=sys.stderr)
            return 2
        print(f"{rule.id} [{rule.family}] — {rule.summary}\n")
        print(rule.explain)
        return 0

    root = find_repo_root(args.root)
    cfg = load_config(root)
    paths = args.paths or None

    try:
        if args.cmd == "lint":
            findings = run_rules(cfg, root, "lint", paths)
            return _gate(findings, cfg, root, args, "lint")
        if args.cmd == "races":
            findings = run_rules(cfg, root, "races", paths)
            return _gate(findings, cfg, root, args, "races")
        if args.cmd == "baseline":
            import os

            from .core import Finding

            findings = (run_rules(cfg, root, "lint", paths)
                        + run_rules(cfg, root, "races", paths))
            bl_path = args.baseline or os.path.join(root, cfg.baseline)
            if args.paths:
                # a path-scoped rebaseline saw only a slice of the tree:
                # out-of-scope entries were not re-observed, not fixed —
                # carry them (and their justifications) over verbatim
                scope = _scope_tuple(args.paths, root)
                for entry in baseline_mod.load_baseline(
                        bl_path).values():
                    if not _in_scope(entry["path"], scope):
                        findings.append(Finding(
                            rule=entry["rule"], path=entry["path"],
                            line=0, symbol=entry.get("symbol", ""),
                            message=entry["message"]))
            n = baseline_mod.write_baseline(bl_path, findings)
            print(f"baseline: {n} entr{'y' if n == 1 else 'ies'} -> "
                  f"{bl_path}")
            return 0
    except FileNotFoundError as e:
        # a typo'd path must FAIL the gate loudly, never report clean
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 2
