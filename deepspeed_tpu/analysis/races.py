"""Thread-safety audit — shared mutable attributes off the lock.

The stack runs real threads: the hang-watchdog poll loop, the bundle
publisher daemon, async checkpoint/snapshot flush workers, the offload
update pool.  PR 4's in-flight save registry exists because one
unlocked cross-thread read shipped; this audit finds the same shape in
source before it ships.

Method (per class, pure AST):

1. **Thread entry points** — methods passed to ``threading.Thread(
   target=...)``/``Timer``/``Executor.submit`` anywhere in the module,
   plus the config's ``thread_roots`` (callback indirection the AST
   cannot see, e.g. the watchdog tick driven by a fake clock in tests).
2. **Reachability** — closure of ``self.X()`` calls from those entries:
   everything those methods run executes on a non-main thread.
3. **Attribute table** — every ``self.attr`` read/write per method,
   annotated with the set of lock attributes held (``with self._lock:``
   blocks, lock-ness decided by the config's ``lock_name_patterns``).
4. **Findings** — an attribute WRITTEN on a thread path and touched in
   any other method where the two accesses share no common lock.
   ``__init__`` accesses are exempt (they happen before the thread
   exists); attributes never written after ``__init__`` are exempt
   (immutable-after-publish).

This is an over-approximation by construction (no happens-before, no
Event-gating recognition) — that is what the baseline's per-entry
justification field is for: every surviving finding is either fixed
with a lock or explained in writing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import (AnalysisConfig, Finding, Rule, SourceModule, call_name,
                   dotted_name, parse_root_spec, register)


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str          # "read" | "write"
    line: int
    locks: frozenset   # lock attr names held at this access


class _ClassAudit:
    """Attribute-access table + thread reachability for one class."""

    def __init__(self, mod: SourceModule, node: ast.ClassDef,
                 cfg: AnalysisConfig):
        self.mod = mod
        self.node = node
        self.cfg = cfg
        self.methods: Dict[str, ast.AST] = {
            item.name: item for item in node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))}
        #: method -> accesses
        self.table: Dict[str, List[_Access]] = {
            name: self._accesses(fn) for name, fn in self.methods.items()}
        self.entries: Set[str] = set()

    # -- access extraction -------------------------------------------------

    def _accesses(self, fn: ast.AST) -> List[_Access]:
        out: List[_Access] = []
        self._visit(fn.body, frozenset(), out)
        return out

    def _visit(self, stmts: List[ast.stmt], locks: frozenset,
               out: List[_Access]) -> None:
        for stmt in stmts:
            held = locks
            if isinstance(stmt, ast.With):
                acquired = set()
                for item in stmt.items:
                    name = dotted_name(item.context_expr)
                    if name and name.startswith("self.") \
                            and self.cfg.lock_like(name[5:]):
                        acquired.add(name[5:])
                if acquired:
                    self._collect_exprs(stmt.items, held, out)
                    self._visit(stmt.body, held | frozenset(acquired), out)
                    continue
            # expressions on this statement (incl. nested defs' bodies —
            # a closure handed to a thread shares the same attrs)
            self._collect_exprs([stmt], held, out,
                                skip_bodies=isinstance(
                                    stmt, (ast.With, ast.If, ast.For,
                                           ast.While, ast.Try)))
            for child_block in _child_blocks(stmt):
                self._visit(child_block, held, out)

    def _collect_exprs(self, nodes, locks: frozenset,
                       out: List[_Access], skip_bodies: bool = False
                       ) -> None:
        for root in nodes:
            for node in ast.walk(root) if not skip_bodies \
                    else _walk_no_blocks(root):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self":
                    if self.cfg.lock_like(node.attr):
                        continue  # the lock object itself
                    kind = ("write" if isinstance(
                        node.ctx, (ast.Store, ast.Del)) else "read")
                    out.append(_Access(node.attr, kind, node.lineno, locks))
                # augmented assign parses target as Store only; the read
                # half of `self.x += 1` must count too
                if isinstance(node, ast.AugAssign) \
                        and isinstance(node.target, ast.Attribute) \
                        and isinstance(node.target.value, ast.Name) \
                        and node.target.value.id == "self" \
                        and not self.cfg.lock_like(node.target.attr):
                    out.append(_Access(node.target.attr, "read",
                                       node.lineno, locks))

    # -- thread reachability ----------------------------------------------

    def find_entries(self) -> None:
        """Methods handed to Thread/Timer/submit anywhere in this class."""
        for fn in self.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node) or ""
                leaf = name.rsplit(".", 1)[-1]
                cands: List[ast.AST] = []
                if leaf in ("Thread", "Timer"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            cands.append(kw.value)
                    if leaf == "Timer" and len(node.args) >= 2:
                        cands.append(node.args[1])
                elif leaf == "submit" and node.args:
                    cands.append(node.args[0])
                elif leaf == "add_done_callback" and node.args:
                    cands.append(node.args[0])
                for cand in cands:
                    target = dotted_name(cand)
                    if target and target.startswith("self."):
                        meth = target[5:]
                        if meth in self.methods:
                            self.entries.add(meth)
                    elif isinstance(cand, ast.Name) \
                            and cand.id in _local_defs(fn):
                        # a nested closure runs on the thread; its
                        # self.X() calls count as entries too
                        for sub in ast.walk(_local_defs(fn)[cand.id]):
                            if isinstance(sub, ast.Call):
                                sname = call_name(sub) or ""
                                if sname.startswith("self.") \
                                        and sname[5:] in self.methods:
                                    self.entries.add(sname[5:])

    def thread_reachable(self) -> Set[str]:
        seen: Set[str] = set()
        queue = list(self.entries)
        while queue:
            meth = queue.pop()
            if meth in seen or meth not in self.methods:
                continue
            seen.add(meth)
            for node in ast.walk(self.methods[meth]):
                if isinstance(node, ast.Call):
                    name = call_name(node) or ""
                    if name.startswith("self.") and name.count(".") == 1:
                        queue.append(name[5:])
        return seen


def _child_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    blocks = []
    for field in ("body", "orelse", "finalbody"):
        b = getattr(stmt, field, None)
        if isinstance(b, list) and b and isinstance(b[0], ast.stmt):
            blocks.append(b)
    for handler in getattr(stmt, "handlers", []) or []:
        blocks.append(handler.body)
    return blocks


def _walk_no_blocks(root: ast.AST):
    """Walk one statement's expressions without descending into nested
    statement blocks (those are visited with their own lock context)."""
    stack = [root]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(node, ast.stmt) \
                and _child_blocks(node):
            continue
        first = False
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt) and _child_blocks(child):
                continue
            stack.append(child)


def _local_defs(fn: ast.AST) -> Dict[str, ast.AST]:
    return {n.name: n for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn}


def _check_thread_safety(mods: List[SourceModule],
                         cfg: AnalysisConfig) -> List[Finding]:
    out: List[Finding] = []
    import fnmatch as _fn
    roots_by_rel: Dict[str, Set[str]] = {}
    for spec in cfg.thread_roots:
        rel, qual = parse_root_spec(spec)
        roots_by_rel.setdefault(rel, set()).add(qual)
    for mod in mods:
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            audit = _ClassAudit(mod, node, cfg)
            audit.find_entries()
            for qual in roots_by_rel.get(mod.rel, ()):
                cls, _, meth = qual.partition(".")
                if cls == node.name and meth in audit.methods:
                    audit.entries.add(meth)
            if not audit.entries:
                continue
            reach = audit.thread_reachable()
            findings = _shared_attr_findings(audit, reach, cfg)
            for attr, writer, wline, other, locks_msg in findings:
                if any(_fn.fnmatch(attr, pat)
                       for pat in cfg.races_ignore_attrs):
                    continue
                f = Finding(
                    rule="thread-unsafe-attr", path=mod.rel, line=wline,
                    symbol=f"{node.name}.{writer}",
                    message=(
                        f"self.{attr} is written on a thread path "
                        f"({node.name}.{writer}) and accessed in "
                        f"{node.name}.{other} with no common lock"
                        f"{locks_msg} — torn/stale reads across the "
                        f"{'/'.join(sorted(audit.entries))} thread "
                        f"boundary"))
                if not mod.suppressed(f.rule, wline):
                    out.append(f)
    return out


def _shared_attr_findings(audit: _ClassAudit, reach: Set[str],
                          cfg: AnalysisConfig
                          ) -> List[Tuple[str, str, int, str, str]]:
    # attr -> [(method, access)]
    by_attr: Dict[str, List[Tuple[str, _Access]]] = {}
    for meth, accesses in audit.table.items():
        for acc in accesses:
            by_attr.setdefault(acc.attr, []).append((meth, acc))
    results: List[Tuple[str, str, int, str, str]] = []
    seen_attr: Set[str] = set()
    for attr, uses in sorted(by_attr.items()):
        # __init__ happens before any thread exists
        live = [(m, a) for m, a in uses if m != "__init__"]
        thread_writes = [(m, a) for m, a in live
                         if m in reach and a.kind == "write"]
        if not thread_writes:
            continue
        others = [(m, a) for m, a in live
                  if m not in reach or (m, a.line) not in
                  {(tm, ta.line) for tm, ta in thread_writes}]
        # at least one access OUTSIDE the writing method
        cross = [(m, a) for m, a in others
                 if m not in {tm for tm, _ in thread_writes}]
        if not cross:
            continue
        for wm, wa in thread_writes:
            for om, oa in cross:
                if wa.locks & oa.locks:
                    continue
                if attr in seen_attr:
                    break
                seen_attr.add(attr)
                locks_msg = ""
                if wa.locks or oa.locks:
                    locks_msg = (f" (writer holds "
                                 f"{sorted(wa.locks) or 'nothing'}, "
                                 f"{om} holds "
                                 f"{sorted(oa.locks) or 'nothing'})")
                results.append((attr, wm, wa.line, om, locks_msg))
                break
    return results


register(Rule(
    id="thread-unsafe-attr", family="races",
    summary="shared mutable attrs written on a thread path off the lock",
    explain=(
        "Builds an attribute-access table over every class that hands a "
        "method to threading.Thread/Timer/Executor.submit (plus the "
        "config's thread_roots for callback indirection), closes "
        "reachability over self.X() calls, and flags attributes written "
        "on a thread path and touched elsewhere with no common lock "
        "held.  Lock-ness of `with self.<attr>:` is decided by "
        "lock_name_patterns; __init__ accesses are exempt "
        "(pre-thread), as are attributes never written after __init__.  "
        "The analysis has no happens-before model — Event-gated and "
        "join()-ordered accesses are reported anyway — so every real "
        "finding is either fixed with the class's lock or baselined "
        "with a written justification (the baseline file's "
        "`justification` field)."),
    check=_check_thread_safety))
