"""JAX/TPU correctness lints — the failure classes PRs 1-5 built runtime
detectors for, caught at review time instead of step 40k.

Five rules, each an AST heuristic over :class:`~.core.SourceModule`:

* ``untracked-jit`` — a ``jax.jit`` under ``runtime/``/``inference/``
  that bypasses ``engine._jit``/``tracked_jit`` is a compile site the
  PR-5 tracker cannot see: its recompiles show up only as mysteriously
  slow steps.
* ``recompile-hazard`` — the three statically-visible recompile causes
  the tracker's cause diffs keep naming after the fact: Python scalars
  closed over inside jitted fns (baked into the trace), shape-dependent
  Python branching (one program per shape class), and ``static_argnums``
  pointing at array-valued parameters (hashed by value — a recompile per
  batch).
* ``host-sync-hot-path`` — ``float()`` / ``.item()`` / ``np.asarray`` /
  ``device_get`` / ``block_until_ready`` reachable from ``train_step``
  serializes device and host; only the declared telemetry fences may do
  it (config ``host_sync_allow`` + inline suppressions).
* ``donated-after-use`` — an array passed at a donated position is dead
  the moment the call dispatches; a later read is use-after-free that
  XLA may or may not catch depending on backend.
* ``raw-collective`` — a ``jax.lax`` collective outside ``comm/``
  bypasses the CommsLogger and silently corrupts the PR-3 desync
  ledger's call-site sequence (two ranks tracing different censuses is
  indistinguishable from a real desync).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from .core import (AnalysisConfig, Finding, Rule, SourceModule, call_name,
                   dotted_name, parse_root_spec, register)

# ---------------------------------------------------------------------------
# shared jit-site discovery
# ---------------------------------------------------------------------------

#: data-moving collectives (axis_index/axis_size are topology queries —
#: no bytes move, the ledger doesn't want them)
COLLECTIVE_OPS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                  "psum_scatter", "all_to_all", "ppermute"}

SYNC_CALLS = {"jax.device_get", "jax.block_until_ready",
              "np.asarray", "np.array", "numpy.asarray", "numpy.array"}
SYNC_METHODS = {"item", "block_until_ready"}


def _is_jax_jit(call: ast.Call) -> bool:
    name = call_name(call)
    return name in ("jax.jit", "jit")


def _is_jit_wrapper(call: ast.Call, cfg: AnalysisConfig) -> bool:
    """Any jit-ish call: jax.jit OR a tracked wrapper (tracked_jit,
    self._jit, engine._jit...)."""
    if _is_jax_jit(call):
        return True
    name = call_name(call)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    return leaf in cfg.tracked_jit_names


def _jit_target(mod: SourceModule, call: ast.Call
                ) -> Optional[ast.AST]:
    """The function being jitted: an inline Lambda/FunctionDef, resolved
    by name within the module when possible."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Lambda):
        return arg
    name = dotted_name(arg)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == leaf:
            return node
    return None


def _params_of(fn: ast.AST) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    return [n for n in names if n not in ("self", "cls")]


# ---------------------------------------------------------------------------
# untracked-jit
# ---------------------------------------------------------------------------


def _check_untracked_jit(mods: List[SourceModule],
                         cfg: AnalysisConfig) -> List[Finding]:
    out: List[Finding] = []
    roots = tuple(r.rstrip("/") + "/" for r in cfg.jit_roots)
    for mod in mods:
        if not mod.rel.startswith(roots):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and _is_jax_jit(node)):
                continue
            qual = mod.qualname(node)
            leaf = qual.rsplit(".", 1)[-1] if qual else ""
            if leaf in cfg.tracked_jit_names:
                continue  # this IS the tracked wrapper
            out.append(mod.finding(
                "untracked-jit", node,
                f"jax.jit bypasses the compile tracker — route through "
                f"tracked_jit(fn, site=..., tracker=get_compile_tracker()) "
                f"or engine._jit so recompiles at this site are recorded "
                f"with cause diffs"))
    return out


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------


def _shape_bearing_names(fn: ast.AST, params: Set[str]) -> Set[str]:
    """Local names assigned from expressions that mention ``.shape`` (or
    ``len(<param>)``/``np.shape``) — transitively shape-derived."""
    derived: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not _mentions_shape(node.value, params, derived):
                continue
            for tgt in node.targets:
                for name_node in ast.walk(tgt):
                    if isinstance(name_node, ast.Name) \
                            and name_node.id not in derived:
                        derived.add(name_node.id)
                        changed = True
    return derived


def _mentions_shape(expr: ast.AST, params: Set[str],
                    derived: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("np.shape", "numpy.shape", "jnp.shape"):
                return True
            if name == "len" and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in params:
                return True
        if isinstance(node, ast.Name) and node.id in derived:
            return True
    return False


def _enclosing_function(mod: SourceModule,
                        target: ast.AST) -> Optional[ast.AST]:
    """The innermost FunctionDef strictly containing ``target``."""
    best: Optional[ast.AST] = None
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not target:
            if any(child is target for child in ast.walk(node)):
                if best is None or (node.lineno > best.lineno):
                    best = node
    return best


def _check_recompile_hazard(mods: List[SourceModule],
                            cfg: AnalysisConfig) -> List[Finding]:
    out: List[Finding] = []
    for mod in mods:
        for call in ast.walk(mod.tree):
            if not (isinstance(call, ast.Call)
                    and _is_jit_wrapper(call, cfg)):
                continue
            fn = _jit_target(mod, call)

            # (c) static_argnums/static_argnames over array-valued params
            for kw in call.keywords:
                if kw.arg == "static_argnums" and fn is not None:
                    params = _params_of(fn)
                    for idx in _int_elems(kw.value):
                        if 0 <= idx < len(params) \
                                and cfg.arrayish(params[idx]):
                            out.append(mod.finding(
                                "recompile-hazard", call,
                                f"static_argnums={idx} points at "
                                f"parameter '{params[idx]}' which looks "
                                f"array-valued — static args are hashed "
                                f"by VALUE, so every new array is a "
                                f"recompile (and unhashable arrays are a "
                                f"TypeError)"))
                if kw.arg == "static_argnames":
                    for name in _str_elems(kw.value):
                        if cfg.arrayish(name):
                            out.append(mod.finding(
                                "recompile-hazard", call,
                                f"static_argnames '{name}' looks "
                                f"array-valued — static args are hashed "
                                f"by VALUE, so every new array is a "
                                f"recompile"))

            if fn is None:
                continue
            params = set(_params_of(fn))

            # (a) shape-dependent Python branching inside the jitted fn
            derived = _shape_bearing_names(fn, params)
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)) \
                        and _mentions_shape(node.test, params, derived):
                    out.append(mod.finding(
                        "recompile-hazard", node,
                        f"Python `{type(node).__name__.lower()}` on a "
                        f"traced shape inside a jitted function — every "
                        f"distinct shape class traces a separate program "
                        f"(the PR-5 tracker will log these as "
                        f"shape_change recompiles); hoist the branch out "
                        f"of the jit or pad to a fixed shape"))

            # (b) Python scalars closed over from the enclosing function
            enclosing = _enclosing_function(mod, fn)
            target_fn = fn
            if enclosing is not None:
                scalar_locals = _scalar_locals(enclosing)
                local_names = _bound_names(target_fn) | params
                reported: Set[str] = set()
                for node in ast.walk(target_fn):
                    if isinstance(node, ast.Name) \
                            and isinstance(node.ctx, ast.Load) \
                            and node.id in scalar_locals \
                            and node.id not in local_names \
                            and node.id not in reported:
                        reported.add(node.id)
                        out.append(mod.finding(
                            "recompile-hazard", node,
                            f"Python scalar '{node.id}' closed over "
                            f"inside a jitted function is baked into the "
                            f"trace — a different value silently "
                            f"recompiles (pass it as a traced argument, "
                            f"or name it in static_context so the "
                            f"tracker's cause diff can say so)"))
    return out


def _int_elems(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _str_elems(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _scalar_locals(fn: ast.AST) -> Set[str]:
    """Names the enclosing function binds to Python scalars: numeric
    literals, int()/float()/len() results, or for-loop indices."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_scalar_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name) \
                and isinstance(node.iter, ast.Call) \
                and call_name(node.iter) == "range":
            out.add(node.target.id)
    return out


def _is_scalar_expr(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Constant) \
            and isinstance(expr.value, (int, float)) \
            and not isinstance(expr.value, bool):
        return True
    if isinstance(expr, ast.Call) \
            and call_name(expr) in ("int", "float", "len"):
        return True
    if isinstance(expr, ast.BinOp):
        return _is_scalar_expr(expr.left) or _is_scalar_expr(expr.right)
    return False


def _bound_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
    return out


# ---------------------------------------------------------------------------
# host-sync-hot-path
# ---------------------------------------------------------------------------


class _ModuleIndex:
    """Name → def tables for one module (methods keyed per class)."""

    def __init__(self, mod: SourceModule):
        self.mod = mod
        self.functions: Dict[str, ast.AST] = {}
        self.methods: Dict[str, Dict[str, ast.AST]] = {}
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                table = self.methods.setdefault(node.name, {})
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        table[item.name] = item

    def resolve(self, qual: str) -> Optional[ast.AST]:
        if "." in qual:
            cls, _, meth = qual.partition(".")
            return self.methods.get(cls, {}).get(meth)
        return self.functions.get(qual)


def _check_host_sync(mods: List[SourceModule],
                     cfg: AnalysisConfig) -> List[Finding]:
    out: List[Finding] = []
    by_rel = {m.rel: m for m in mods}
    allow = set(cfg.host_sync_allow)

    def allowed(qual: str) -> bool:
        return qual in allow or qual.rsplit(".", 1)[-1] in allow

    for spec in cfg.hot_path_roots:
        rel, root_qual = parse_root_spec(spec)
        mod = by_rel.get(rel)
        if mod is None:
            continue
        index = _ModuleIndex(mod)
        root = index.resolve(root_qual)
        if root is None:
            continue
        cls_name = root_qual.partition(".")[0] if "." in root_qual else None
        # reachability: same-class methods via self.X(), same-module
        # functions by name.  Cross-module descent is deliberately out of
        # scope (name-based guessing across files produces noise, and the
        # hot path's host syncs live in the engine module); add more
        # hot_path_roots to cover indirection.
        seen: Set[str] = set()
        queue: List[Tuple[str, ast.AST]] = [(root_qual, root)]
        while queue:
            qual, fn = queue.pop()
            if qual in seen or allowed(qual):
                continue
            seen.add(qual)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                if name.startswith("self.") and name.count(".") == 1 \
                        and cls_name is not None:
                    meth = name.split(".", 1)[1]
                    target = index.methods.get(cls_name, {}).get(meth)
                    if target is not None:
                        queue.append((f"{cls_name}.{meth}", target))
                elif "." not in name and name in index.functions:
                    queue.append((name, index.functions[name]))
                # sync detection at this call site
                msg = _sync_message(node, name)
                if msg is not None:
                    out.append(mod.finding(
                        "host-sync-hot-path", node,
                        f"{msg} reachable from {root_qual} — a device→"
                        f"host sync serializes dispatch on the step hot "
                        f"path; move it behind the telemetry fence "
                        f"(host_sync_allow) or out of the step"))
    return out


def _sync_message(call: ast.Call, name: str) -> Optional[str]:
    if name in SYNC_CALLS:
        return f"{name}(...)"
    leaf = name.rsplit(".", 1)[-1]
    if "." in name and leaf in SYNC_METHODS:
        return f".{leaf}()"
    if name == "float" and call.args \
            and not isinstance(call.args[0], ast.Constant):
        return "float(<traced value>)"
    return None


# ---------------------------------------------------------------------------
# donated-after-use
# ---------------------------------------------------------------------------


def _donate_spec(call: ast.Call) -> Tuple[Tuple[int, ...],
                                          Tuple[str, ...]]:
    """(positions, keyword names) donated by a jit call — both spellings
    can appear on one call and donate different arguments."""
    pos: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            pos = tuple(_int_elems(kw.value))
        elif kw.arg == "donate_argnames":
            names = tuple(_str_elems(kw.value))
    return pos, names


def _check_donated_reuse(mods: List[SourceModule],
                         cfg: AnalysisConfig) -> List[Finding]:
    out: List[Finding] = []
    for mod in mods:
        for scope in ast.walk(mod.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            # donated callables bound in THIS scope: name -> (pos, names)
            donators: Dict[str, Tuple[Tuple[int, ...],
                                      Tuple[str, ...]]] = {}
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and _is_jit_wrapper(node.value, cfg):
                    spec = _donate_spec(node.value)
                    if not (spec[0] or spec[1]):
                        continue
                    for tgt in node.targets:
                        name = dotted_name(tgt)
                        if name is not None:
                            donators[name] = spec
            if not donators:
                continue
            # call sites + later reads of the donated argument
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func)
                if callee not in donators:
                    continue
                d_pos, d_names = donators[callee]
                donated_args = []
                for pos in d_pos:
                    if pos < len(node.args):
                        donated_args.append((f"position {pos}",
                                             node.args[pos]))
                for kw in node.keywords:
                    if kw.arg in d_names:
                        donated_args.append((f"argname '{kw.arg}'",
                                             kw.value))
                for where, arg in donated_args:
                    donated = dotted_name(arg)
                    if donated is None:
                        continue
                    # `x = f(x)` rebinds the name to the RESULT — later
                    # reads see the new buffer, not the donated one
                    rebound = _rebinds(scope, node, donated)
                    if rebound:
                        continue
                    for later in ast.walk(scope):
                        if getattr(later, "lineno", 0) <= node.lineno:
                            continue
                        if isinstance(later, (ast.Name, ast.Attribute)) \
                                and isinstance(getattr(later, "ctx", None),
                                               ast.Load) \
                                and dotted_name(later) == donated:
                            out.append(mod.finding(
                                "donated-after-use", later,
                                f"'{donated}' was donated to "
                                f"{callee}(...) (donate {where}) "
                                f"and read afterwards — donated buffers "
                                f"are invalidated at dispatch; rebind "
                                f"the result or drop the donation"))
                            break
    return out


def _rebinds(scope: ast.AST, call: ast.Call, name: str) -> bool:
    """Does any assignment in ``scope`` whose value contains ``call``
    rebind ``name``?  (the `x = f(x)` / `self.pool = f(self.pool)`
    donation idiom)"""
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) \
                and any(child is call for child in ast.walk(node.value)):
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if dotted_name(sub) == name:
                        return True
    return False


# ---------------------------------------------------------------------------
# raw-collective
# ---------------------------------------------------------------------------


def _check_raw_collective(mods: List[SourceModule],
                          cfg: AnalysisConfig) -> List[Finding]:
    out: List[Finding] = []
    home = cfg.collective_home.rstrip("/") + "/"
    for mod in mods:
        if mod.rel.startswith(home):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            parts = name.split(".")
            if parts[-1] in COLLECTIVE_OPS and len(parts) >= 2 \
                    and parts[-2] == "lax":
                verb = {"psum_scatter": "reduce_scatter_in_graph",
                        "all_gather": "all_gather_in_graph",
                        "all_to_all": "all_to_all_in_graph"}.get(
                            parts[-1], parts[-1])
                fix = (f"use deepspeed_tpu.comm.{verb}"
                       if parts[-1] != "pmin" else
                       "comm/ has no pmin verb yet — add an instrumented "
                       "wrapper there (mirroring pmax) rather than "
                       "calling lax directly")
                out.append(mod.finding(
                    "raw-collective", node,
                    f"raw {name} outside comm/ bypasses the CommsLogger "
                    f"— it never reaches the CollectiveLedger, so two "
                    f"ranks tracing it see different censuses and the "
                    f"desync detector reports a phantom divergence; "
                    f"{fix}"))
    return out


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

register(Rule(
    id="untracked-jit", family="lint",
    summary="jax.jit in runtime//inference/ outside the compile tracker",
    explain=(
        "PR 5 wired every ENGINE jit site through tracked_jit so each "
        "compile/recompile lands in the tracker with a structured cause "
        "diff.  Any jax.jit under runtime/ or inference/ that does not "
        "ride that path is a blind spot: its recompiles burn step time "
        "with no event, no cause, no bundle entry.  Fix: "
        "tracked_jit(fn, site='pkg/what', tracker=get_compile_tracker(), "
        "**jit_kwargs) — with tracking disabled this IS jax.jit, so the "
        "rewrite costs nothing.  Config: jit_roots, tracked_jit_names."),
    check=_check_untracked_jit))

register(Rule(
    id="recompile-hazard", family="lint",
    summary="trace-baked Python scalars, shape branches, static arrays",
    explain=(
        "Three statically-visible causes of the recompiles the PR-5 "
        "tracker keeps diagnosing at runtime: (1) a Python scalar closed "
        "over inside a jitted fn is baked into the trace — changing it "
        "recompiles with a 'static' cause at best, silently at worst; "
        "(2) an `if`/`while` on a traced .shape forks one XLA program "
        "per shape class; (3) static_argnums over an array-valued "
        "parameter hashes arrays by value — a recompile per batch.  "
        "Findings here are heuristic (name-based resolution, no type "
        "inference): suppress with `# dslint: disable=recompile-hazard` "
        "where the scalar is deliberately static and named in "
        "static_context.  Config: array_param_names."),
    check=_check_recompile_hazard))

register(Rule(
    id="host-sync-hot-path", family="lint",
    summary="device→host syncs reachable from train_step",
    explain=(
        "float()/.item()/np.asarray/jax.device_get/block_until_ready on "
        "the step hot path force the host to wait for the device and "
        "kill dispatch pipelining — the goodput ledger then charges the "
        "wait to 'productive' time where nobody looks for it.  The "
        "engine's DELIBERATE fences (device-true step timing for "
        "telemetry/autotuning) are declared in host_sync_allow or "
        "suppressed inline where the fence is the point.  Reachability "
        "is same-module only (self.* methods + module functions from "
        "each hot_path_roots entry); add roots to cover indirection."),
    check=_check_host_sync))

register(Rule(
    id="donated-after-use", family="lint",
    summary="reads of a buffer after passing it at a donated position",
    explain=(
        "donate_argnums hands the argument's buffer to XLA for reuse — "
        "after the call dispatches, the Python array is logically dead. "
        "Reading it again returns garbage or raises depending on "
        "backend/timing (the worst kind of bug: passes on CPU tests, "
        "corrupts on TPU).  The rule tracks donated callables bound in "
        "the same function scope (f = jax.jit(..., donate_argnums=...)) "
        "and flags later reads of donated arguments; the `x = f(x)` "
        "rebinding idiom is recognized as safe."),
    check=_check_donated_reuse))

register(Rule(
    id="raw-collective", family="lint",
    summary="jax.lax collectives invoked outside comm/",
    explain=(
        "comm/ wraps every in-graph collective so the CommsLogger census "
        "feeds the CollectiveLedger — the hash-chained per-rank sequence "
        "the PR-3 desync detector compares across hosts.  A raw jax.lax "
        "collective anywhere else is invisible to that census: ranks "
        "executing it still move bytes, but their ledgers no longer "
        "describe the same program, so first-divergence analysis points "
        "at the wrong collective.  Fix: the matching comm verb (psum, "
        "pmean, pmax, all_gather_in_graph, reduce_scatter_in_graph, "
        "all_to_all_in_graph, ppermute) — same lax op underneath, plus "
        "the census record.  axis_index/axis_size are topology queries, "
        "not collectives, and are not flagged.  Config: collective_home."),
    check=_check_raw_collective))
