"""Findings baseline — the reviewed debt ledger dslint gates against.

The baseline is a checked-in JSON file mapping known findings to (per
entry, optional) written justifications.  ``analysis lint`` exits 3 on
any finding NOT in the baseline; ``analysis baseline`` regenerates the
file from the current findings, PRESERVING justifications of entries
that still match — so re-baselining after a cleanup never loses the
reasoning attached to what remains.

Matching is by ``Finding.key()`` — ``(rule, path, symbol, message)``,
never line numbers (every unrelated edit above a finding would
otherwise churn the file).  Stale entries (baselined findings that no
longer fire) are reported as a note, not an error: deleting them is the
next ``baseline`` run's job.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Tuple

from .core import Finding

BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[Tuple[str, str, str, str],
                                     Dict[str, Any]]:
    """Baseline entries keyed for matching; {} when the file is absent
    (first run: everything is new)."""
    if not os.path.isfile(path):
        return {}
    with open(path, "r") as fh:
        data = json.load(fh)
    out = {}
    for entry in data.get("entries", []):
        key = (entry["rule"], entry["path"], entry.get("symbol", ""),
               entry["message"])
        out[key] = entry
    return out


def write_baseline(path: str, findings: List[Finding]) -> int:
    """Write the baseline for ``findings``, carrying over justifications
    from a pre-existing file where the entry still matches."""
    old = load_baseline(path)
    entries = []
    for f in sorted(set(f.key() for f in findings)):
        rule, rel, symbol, message = f
        entry: Dict[str, Any] = {"rule": rule, "path": rel,
                                 "symbol": symbol, "message": message}
        prev = old.get(f)
        if prev and prev.get("justification"):
            entry["justification"] = prev["justification"]
        entries.append(entry)
    payload = {
        "version": BASELINE_VERSION,
        "tool": "python -m deepspeed_tpu.analysis baseline",
        "note": ("Known findings dslint tolerates.  Every entry is "
                 "true-but-deferred; `justification` says why it is "
                 "deferred.  Fix the code, then re-run `baseline` to "
                 "shrink this file — never hand-add entries to silence "
                 "a new finding."),
        "entries": entries,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)
    return len(entries)


def partition(findings: List[Finding], baseline: Dict,
              ran_rules: Any = None) -> Tuple[
        List[Finding], List[Finding], List[Dict[str, Any]]]:
    """(new, known, stale): findings not in the baseline, findings in
    it, and baseline entries nothing matched.  ``ran_rules`` scopes the
    staleness check to rules that actually executed — ``lint`` must not
    call the races entries stale (and vice versa)."""
    new, known = [], []
    matched = set()
    for f in findings:
        if f.key() in baseline:
            known.append(f)
            matched.add(f.key())
        else:
            new.append(f)
    stale = [entry for key, entry in baseline.items()
             if key not in matched
             and (ran_rules is None or key[0] in ran_rules)]
    return new, known, stale
