"""Monitor fan-out: (tag, value, step) events → TensorBoard / W&B / CSV.

Capability parity with the reference ``deepspeed/monitor/`` [K]:
``MonitorMaster`` dispatches to every enabled backend; config groups
``tensorboard``, ``wandb``, ``csv_monitor`` (§5.5).  Comet/nebula are
documented gaps (SURVEY §7 non-ported list).
"""

from __future__ import annotations

import csv
import os
from typing import Any, List, Tuple

from ..utils.logging import logger

Event = Tuple[str, Any, int]  # (tag, value, global_step)


class TensorBoardMonitor:
    def __init__(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.writer = None
        if self.enabled:
            try:
                from tensorflow.summary import create_file_writer  # type: ignore

                path = os.path.join(cfg.output_path or "runs", cfg.job_name)
                self.writer = create_file_writer(path)
            except Exception as e:  # tf absent or broken — degrade, don't die
                logger.warning(f"tensorboard monitor disabled: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.writer:
            return
        import tensorflow as tf  # type: ignore

        with self.writer.as_default():
            for tag, value, step in events:
                tf.summary.scalar(tag, float(value), step=step)


class WandbMonitor:
    def __init__(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.run = None
        if self.enabled:
            try:
                import wandb  # type: ignore

                self.run = wandb.init(project=cfg.project, group=cfg.group,
                                      entity=cfg.team)
            except Exception as e:
                logger.warning(f"wandb monitor disabled: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.run:
            return
        for tag, value, step in events:
            self.run.log({tag: float(value)}, step=step)


class CSVMonitor:
    def __init__(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.path = None
        if self.enabled:
            base = os.path.join(cfg.output_path or "csv_logs", cfg.job_name)
            os.makedirs(base, exist_ok=True)
            self.path = os.path.join(base, "metrics.csv")
            if not os.path.exists(self.path):
                with open(self.path, "w", newline="") as fh:
                    csv.writer(fh).writerow(["tag", "value", "step"])

    def write_events(self, events: List[Event]) -> None:
        if not self.path:
            return
        with open(self.path, "a", newline="") as fh:
            w = csv.writer(fh)
            for tag, value, step in events:
                w.writerow([tag, float(value), step])


class MonitorMaster:
    """Fans every event out to all enabled backends (reference name)."""

    def __init__(self, ds_config) -> None:
        self.backends = []
        self.tb = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb = WandbMonitor(ds_config.wandb)
        self.csv = CSVMonitor(ds_config.csv_monitor)
        for backend in (self.tb, self.wandb, self.csv):
            if backend.enabled:
                self.backends.append(backend)

    @property
    def enabled(self) -> bool:
        return bool(self.backends)

    def write_events(self, events: List[Event]) -> None:
        for backend in self.backends:
            backend.write_events(events)
