"""Monitor fan-out: (tag, value, step) events → TensorBoard / W&B / CSV /
the unified telemetry registry.

Capability parity with the reference ``deepspeed/monitor/`` [K]:
``MonitorMaster`` dispatches to every enabled backend; config groups
``tensorboard``, ``wandb``, ``csv_monitor`` plus the repo-native
``telemetry`` group (§5.5).  Comet/nebula are documented gaps (SURVEY §7
non-ported list).
"""

from __future__ import annotations

import csv
import os
from typing import Any, List, Tuple

from ..utils.logging import logger

Event = Tuple[str, Any, int]  # (tag, value, global_step)


class TensorBoardMonitor:
    def __init__(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.writer = None
        if self.enabled:
            try:
                from tensorflow.summary import create_file_writer  # type: ignore

                path = os.path.join(cfg.output_path or "runs", cfg.job_name)
                self.writer = create_file_writer(path)
            except Exception as e:  # tf absent or broken — degrade, don't die
                logger.warning(f"tensorboard monitor disabled: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.writer:
            return
        import tensorflow as tf  # type: ignore

        with self.writer.as_default():
            for tag, value, step in events:
                tf.summary.scalar(tag, float(value), step=step)


class WandbMonitor:
    def __init__(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.run = None
        if self.enabled:
            try:
                import wandb  # type: ignore

                self.run = wandb.init(project=cfg.project, group=cfg.group,
                                      entity=cfg.team)
            except Exception as e:
                logger.warning(f"wandb monitor disabled: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if not self.run:
            return
        for tag, value, step in events:
            self.run.log({tag: float(value)}, step=step)


class CSVMonitor:
    def __init__(self, cfg) -> None:
        self.enabled = cfg.enabled
        self.path = None
        if self.enabled:
            base = os.path.join(cfg.output_path or "csv_logs", cfg.job_name)
            os.makedirs(base, exist_ok=True)
            self.path = os.path.join(base, "metrics.csv")
            if not os.path.exists(self.path):
                with open(self.path, "w", newline="") as fh:
                    csv.writer(fh).writerow(["tag", "value", "step"])

    def write_events(self, events: List[Event]) -> None:
        if not self.path:
            return
        with open(self.path, "a", newline="") as fh:
            w = csv.writer(fh)
            for tag, value, step in events:
                w.writerow([tag, float(value), step])


class TelemetryMonitor:
    """Fourth backend: events land in the unified telemetry registry
    (``deepspeed_tpu/telemetry/``) as gauges + JSONL ``monitor`` events —
    so the existing ``monitor.write_events`` flow and the engine's
    per-step records share one exporter pipeline."""

    def __init__(self, cfg) -> None:
        self.enabled = bool(getattr(cfg, "enabled", False))
        self.hub = None
        if self.enabled:
            try:
                from ..telemetry import configure_from_config

                self.hub = configure_from_config(cfg)
            except Exception as e:  # degrade like the other backends
                logger.warning(f"telemetry monitor disabled: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if self.hub is None:
            return
        for tag, value, step in events:
            self.hub.set_gauge(tag, float(value))
            self.hub.emit_event("monitor", {"tag": tag,
                                            "value": float(value),
                                            "step": int(step)})


class MonitorMaster:
    """Fans every event out to all enabled backends (reference name)."""

    def __init__(self, ds_config) -> None:
        self.backends = []
        self.tb = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb = WandbMonitor(ds_config.wandb)
        self.csv = CSVMonitor(ds_config.csv_monitor)
        self.telemetry = TelemetryMonitor(getattr(ds_config, "telemetry",
                                                  None))
        for backend in (self.tb, self.wandb, self.csv, self.telemetry):
            if backend.enabled:
                self.backends.append(backend)

    @property
    def enabled(self) -> bool:
        return bool(self.backends)

    def write_events(self, events: List[Event]) -> None:
        for backend in self.backends:
            backend.write_events(events)

    def write_health_events(self, events) -> None:
        """Fan out :class:`~..telemetry.health.HealthEvent` anomalies as
        ``Health/<kind>`` scalars (the event's statistic — z-score,
        ratio, scale — as the value) so a TensorBoard/W&B dashboard shows
        anomaly markers on the same step axis as the training curves."""
        self.write_events([(f"Health/{e.kind}", float(e.value), int(e.step))
                           for e in events])
