"""Cluster debug-bundle aggregation — N black boxes, ONE artifact.

PR 2 left an N-host incident as N scattered local bundle directories;
this module is the cross-host half (the ROADMAP follow-up): each host
publishes its flight-recorder bundle through the elastic rendezvous
key-value store (chunked + size-capped — the store is a control plane,
not a blob store), and rank 0 / an operator assembles ONE cluster
archive::

    cluster-<utc>/
      cluster_manifest.json     # per-host step index, heartbeat ages,
                                # straggler stats, comm-census deltas,
                                # collective-desync report
      hosts/<node>/bundle-*/    # every host's full debug bundle

Store protocol (all JSON values through ``RendezvousClient``):

* ``debug/req``              — collect-request counter; the operator (or
  rank 0) bumps it, every host's :class:`BundlePublisher` answers with a
  FRESH dump.
* ``debug/chunk/<node>/<i>`` — base64 chunks of the host's tar.gz.
* ``debug/pub/<node>``       — publication meta (``req``, chunk count,
  bytes, dropped files); written LAST, so it is the commit point.

A shared-filesystem path is the fallback transport for deployments where
hosts mount common storage but the store is gone (post-crash collection).

Publishing is also event-driven: the publisher's periodic ``tick`` (the
elastic agent calls it from its heartbeat loop) notices a new local
bundle (watchdog trip, crash hook) and pushes it without an operator
request — the archive a collect later assembles already holds the trip
evidence even if the tripping host died in between.
"""

from __future__ import annotations

import base64
import io
import json
import os
import tarfile
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import debug_once, logger
from .collective_ledger import (find_first_divergence,
                                format_divergence_report)
from .flight_recorder import BUNDLE_MANIFEST

CLUSTER_MANIFEST = "cluster_manifest.json"
#: the clock-aligned merged trace `telemetry collect` assembles from
#: every host bundle's trace.json (ISSUE 13): one Chrome-trace document
#: with a lane (pid) per process, span timestamps shifted onto the
#: shared store clock via each tracer's clock_sync metadata
CLUSTER_TRACE = "cluster_trace.json"
#: every node's serving request-record publication at collect time
#: (ISSUE 15): the raw per-node docs `serving trace` assembles from,
#: persisted so a post-mortem archive can replay the assembly offline —
#: and folded into CLUSTER_TRACE as per-node request lanes
CLUSTER_REQUESTS = "cluster_requests.json"
_REQ_KEY = "debug/req"


def _meta_key(node_id: str) -> str:
    return f"debug/pub/{node_id}"


def _partial_key(node_id: str) -> str:
    return f"debug/partial/{node_id}"


# ---------------------------------------------------------------------------
# publish side (every host)
# ---------------------------------------------------------------------------

def _normalize_tarinfo(ti: tarfile.TarInfo) -> tarfile.TarInfo:
    """Strip everything non-content from a tar member: the archive of a
    directory must be a pure function of its FILE CONTENTS, so a holder
    that re-tars an extracted tier-2 replica reproduces the exact bytes
    the owner's published sha256 was computed over."""
    ti.mtime = 0
    ti.uid = ti.gid = 0
    ti.uname = ti.gname = ""
    ti.mode = 0o755 if ti.isdir() else 0o644
    return ti


def _tar_dir(src_dir: str, max_bytes: int, priority_file: str = "",
             recursive: bool = False) -> tuple:
    """tar.gz ``src_dir`` into memory, smallest files first under the
    size cap — ``priority_file`` (e.g. the bundle manifest) is always
    included; the biggest side file is what gets dropped.  Returns
    ``(data, dropped_names)``.  The generic half of the store transport
    — the resilience plane ships snapshot trees (``recursive=True``)
    through the same path debug bundles use.

    The archive is DETERMINISTIC (gzip mtime zeroed, members fully
    ordered, stat metadata normalized): the P2P replica transport
    checksum-gates on the tar's sha256, and a holder serving a replica
    it re-extracted must be able to rebuild byte-identical data.
    (Caveat: determinism assumes one zlib build across the gang — a
    mismatched holder fails the gate loudly and the fetch falls
    through, never restores silently-wrong bytes.)"""
    import gzip

    name = os.path.basename(src_dir.rstrip(os.sep))
    if recursive:
        entries = []
        for root, _dirs, files in os.walk(src_dir):
            for f in files:
                p = os.path.join(root, f)
                entries.append(os.path.relpath(p, src_dir))
    else:
        entries = [f for f in os.listdir(src_dir)
                   if os.path.isfile(os.path.join(src_dir, f))]
    entries.sort(key=lambda f: (f != priority_file,
                                os.path.getsize(os.path.join(src_dir, f)),
                                f))
    dropped: List[str] = []
    buf = io.BytesIO()
    budget = int(max_bytes)
    with gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as gz:
        with tarfile.open(fileobj=gz, mode="w") as tar:
            for f in entries:
                p = os.path.join(src_dir, f)
                size = os.path.getsize(p)
                # raw-size budget (compression only helps); priority
                # always in
                if f != priority_file and size > budget:
                    dropped.append(f)
                    continue
                tar.add(p, arcname=f"{name}/{f}",
                        filter=_normalize_tarinfo)
                budget -= size
    return buf.getvalue(), dropped


def push_dir_chunked(client: Any, meta_key: str, chunk_prefix: str,
                     src_dir: str, chunk_bytes: int = 256 * 1024,
                     max_bytes: int = 32 * 1024 * 1024,
                     priority_file: str = "", recursive: bool = False,
                     meta_extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Ship a directory through the key-value store as base64 tar.gz
    chunks under ``<chunk_prefix>/<i>``, committing with ``meta_key``
    written LAST.  Shared by bundle publication and resilience buddy
    snapshot replication."""
    data, dropped = _tar_dir(src_dir, max_bytes, priority_file=priority_file,
                             recursive=recursive)
    b64 = base64.b64encode(data).decode("ascii")
    step = max(1, int(chunk_bytes))
    chunks = [b64[i:i + step] for i in range(0, len(b64), step)] or [""]
    for i, ch in enumerate(chunks):
        client.set(f"{chunk_prefix}/{i}", ch)
    meta = {"bundle": os.path.basename(src_dir), "n": len(chunks),
            "bytes": len(data), "dropped": dropped, "ts": time.time(),
            **(meta_extra or {})}
    client.set(meta_key, meta)  # commit point: meta LAST
    return meta


def fetch_dir_chunked(client: Any, meta_key: str, chunk_prefix: str,
                      out_dir: str) -> Optional[str]:
    """Inverse of :func:`push_dir_chunked`: pull + unpack into
    ``out_dir``; returns the extracted directory, or None when nothing
    is published under ``meta_key``."""
    meta = client.get(meta_key)
    if not isinstance(meta, dict):
        return None
    b64 = "".join(client.get(f"{chunk_prefix}/{i}") or ""
                  for i in range(int(meta["n"])))
    data = base64.b64decode(b64)
    os.makedirs(out_dir, exist_ok=True)
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
        _safe_extract(tar, out_dir)
    return os.path.join(out_dir, meta["bundle"])


def publish_bundle(client: Any, node_id: str, bundle_dir: str,
                   req_id: int = 0, chunk_bytes: int = 256 * 1024,
                   max_bundle_bytes: int = 32 * 1024 * 1024) -> Dict[str, Any]:
    """Push one host's bundle through the store; returns the meta dict."""
    return push_dir_chunked(
        client, _meta_key(node_id), f"debug/chunk/{node_id}", bundle_dir,
        chunk_bytes=chunk_bytes, max_bytes=max_bundle_bytes,
        priority_file=BUNDLE_MANIFEST, meta_extra={"req": int(req_id)})


def _safe_extract(tar: tarfile.TarFile, out_dir: str) -> None:
    for m in tar.getmembers():
        p = os.path.normpath(m.name)
        if p.startswith("..") or os.path.isabs(p) or not (m.isfile()
                                                          or m.isdir()):
            raise ValueError(f"unsafe tar member {m.name!r}")
    tar.extractall(out_dir)


def fetch_bundle(client: Any, node_id: str, out_dir: str) -> Optional[str]:
    """Pull + unpack one host's published bundle into ``out_dir``;
    returns the extracted bundle path, or None if nothing is published."""
    return fetch_dir_chunked(client, _meta_key(node_id),
                             f"debug/chunk/{node_id}", out_dir)


def publish_bundle_fs(node_id: str, bundle_dir: str, shared_fs_path: str,
                      req_id: int = 0) -> str:
    """Shared-filesystem fallback transport: copy the bundle under
    ``<shared>/<node>/`` and stamp a meta file (same commit-last rule)."""
    import shutil

    dest_root = os.path.join(shared_fs_path, node_id)
    dest = os.path.join(dest_root, os.path.basename(bundle_dir))
    os.makedirs(dest_root, exist_ok=True)
    if os.path.isdir(dest):
        shutil.rmtree(dest)
    shutil.copytree(bundle_dir, dest)
    with open(os.path.join(dest_root, "meta.json"), "w") as fh:
        json.dump({"req": int(req_id),
                   "bundle": os.path.basename(bundle_dir),
                   "ts": time.time()}, fh)
    return dest


class BundlePublisher:
    """Host-side service: answer collect requests and push fresh local
    bundles.  The elastic agent calls :meth:`tick` from its heartbeat
    loop; anything with a ``RendezvousClient``-shaped object can drive
    it (the acceptance test runs three in one process)."""

    def __init__(self, node_id: str, recorder: Any = None,
                 chunk_bytes: int = 256 * 1024,
                 max_bundle_bytes: int = 32 * 1024 * 1024,
                 shared_fs_path: str = "",
                 telemetry_push_every_s: float = 2.0):
        self.node_id = node_id
        #: None = resolve the process-global recorder at tick time (the
        #: ledger reaches bundles through its flight-recorder context
        #: provider, so the publisher never touches it directly)
        self._recorder = recorder
        self.chunk_bytes = int(chunk_bytes)
        self.max_bundle_bytes = int(max_bundle_bytes)
        self.shared_fs_path = shared_fs_path
        # start at 0, not the current counter: an outstanding request from
        # before this host joined still deserves an answer (one redundant
        # dump beats a collector timing out on a silent host)
        self._last_req_served = 0
        self._last_published: Optional[str] = None
        #: watchdog trips already answered with a PARTIAL push
        self._trips_pushed = 0
        #: cross-process rollup publish cadence (telemetry/rollup.py):
        #: the tick ships the registry snapshot + step-stream batch at
        #: most this often (<= 0 disables the push entirely)
        self.telemetry_push_every_s = float(telemetry_push_every_s)
        self._last_telemetry_push = 0.0
        # the agent's heartbeat loop and the worker-side daemon (subprocess
        # mode) may drive the same publisher — one beat at a time
        self._tick_lock = threading.Lock()
        self._daemon: Optional[threading.Thread] = None
        self._daemon_stop = threading.Event()

    def recorder(self) -> Any:
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import get_flight_recorder

        return get_flight_recorder()

    def _publish(self, client: Any, bundle_dir: str, req_id: int) -> None:
        publish_bundle(client, self.node_id, bundle_dir, req_id=req_id,
                       chunk_bytes=self.chunk_bytes,
                       max_bundle_bytes=self.max_bundle_bytes)
        if self.shared_fs_path:
            try:
                publish_bundle_fs(self.node_id, bundle_dir,
                                  self.shared_fs_path, req_id=req_id)
            except OSError as e:
                logger.warning(f"aggregator: shared-fs publish failed: "
                               f"{e!r}")
        self._last_published = bundle_dir

    def _partial_payload(self, wd: Any) -> Dict[str, Any]:
        """A hung host's last words: the watchdog's liveness summary
        (step index + collective-ledger seq/hash), the ledger TAIL, and
        every thread's Python stack — small enough to ship as ONE store
        value even when the host can't complete a full bundle dump."""
        payload: Dict[str, Any] = {"ts": time.time(), "node": self.node_id,
                                   "trips": int(getattr(wd, "trips", 0)),
                                   "reason": "watchdog trip"}
        try:
            payload["liveness"] = wd.heartbeat_payload()
        except Exception as e:
            payload["liveness"] = {"error": repr(e)}
        try:
            from .collective_ledger import get_collective_ledger

            led = get_collective_ledger()
            if led.enabled:
                payload["ledger_tail"] = led.tail()
        except Exception as e:
            payload["ledger_tail"] = {"error": repr(e)}
        try:
            # pure-python stack walk: faulthandler needs a real fd, and a
            # heartbeat thread mid-incident may not be able to open one
            import sys as _sys
            import traceback as _tb

            frames = _sys._current_frames()
            names = {t.ident: t.name for t in threading.enumerate()}
            stacks = []
            for ident, frame in frames.items():
                stacks.append(f"--- thread {names.get(ident, ident)}\n"
                              + "".join(_tb.format_stack(frame)))
            payload["stacks"] = "\n".join(stacks)[:32768]
        except Exception as e:
            payload["stacks"] = f"unavailable: {e!r}"
        return payload

    def _maybe_push_telemetry(self, client: Any) -> None:
        """Cadence-gated cross-process telemetry publish (the tentpole
        transport): estimate/refresh the store-clock offset, then ship
        the registry snapshot and the step stream's unacked batch.
        Raises the client's ConnectionError family on an outage so the
        caller's degraded path counts and retries it."""
        if self.telemetry_push_every_s <= 0:
            return
        now = time.monotonic()
        if now - self._last_telemetry_push < self.telemetry_push_every_s:
            return
        from .clocksync import maybe_sync_clock
        from .rollup import push_node_telemetry

        maybe_sync_clock(client, node_id=self.node_id)
        push_node_telemetry(client, self.node_id)
        # stamp only after SUCCESS: a degraded beat retries immediately
        # on the next healthy tick instead of waiting out the cadence
        self._last_telemetry_push = now

    def _maybe_push_partial(self, client: Any) -> None:
        """ROADMAP follow-up (ISSUE 4 satellite): when the watchdog
        trips, event-push a best-effort PARTIAL ledger (tail + stacks)
        straight from the heartbeat thread — the worker may be hung too
        hard to answer a collect request or finish a full dump, but one
        ``client.set`` of pre-collected state almost always lands."""
        from .watchdog import get_watchdog

        wd = get_watchdog()
        if wd is None:
            return
        trips = int(getattr(wd, "trips", 0))
        if trips <= self._trips_pushed:
            return
        client.set(_partial_key(self.node_id), self._partial_payload(wd))
        # mark served only once the set SUCCEEDED: a store hiccup (likely
        # mid-incident) must leave the push pending for the next beat
        self._trips_pushed = trips
        from . import get_telemetry

        get_telemetry().inc_counter(
            "aggregator/partial_pushes",
            help="best-effort partial-ledger publications on watchdog trip")

    def tick(self, client: Any) -> Optional[str]:
        """One service beat: answer a pending collect request with a
        FRESH dump, else push a not-yet-published local bundle (watchdog
        trip / crash hook).  Returns the published path, if any.

        Store-down beats DEGRADE instead of raising: nothing is marked
        served/published on a failed beat (the request and the pending
        bundle are the bounded buffer — both retry on the next healthy
        tick), and the skipped beat is counted so the outage is visible
        in the registry."""
        with self._tick_lock:
            try:
                # FIRST and unconditionally: the cheap partial push must
                # not wait behind a full dump that may itself be stuck
                self._maybe_push_partial(client)
            except Exception as e:
                # best-effort by definition
                debug_once("aggregator/partial_push",
                           f"partial-ledger push failed ({e!r}); "
                           f"retrying next tick")
            try:
                # cross-process telemetry (ISSUE 13): clock sync (cheap
                # no-op unless the store generation moved) + the metrics
                # snapshot / step-record batch at the configured cadence.
                # A store-down failure lands in the ConnectionError
                # branch below: the beat degrades, the step batch stays
                # buffered in its bounded ring, and the next healthy
                # beat flushes it exactly once (the rollup dedups by
                # sequence).
                self._maybe_push_telemetry(client)
                # fleet profiler command channel (ISSUE 20): the same
                # beat that answers collect requests arms/publishes
                # capture windows — no new threads, same degraded path
                from .profiler import get_profiler_plane

                plane = get_profiler_plane()
                if plane is not None:
                    plane.poll(client)
                req = int(client.get(_REQ_KEY) or 0)
                rec = self.recorder()
                if req > self._last_req_served:
                    # dump BEFORE marking served: a failed dump (ENOSPC
                    # mid-incident) leaves the request pending so the
                    # next tick really does retry; a failed PUBLISH after
                    # a good dump self-heals via the last_bundle_path
                    # branch below
                    bundle = rec.dump(f"operator collect request #{req}")
                    self._last_req_served = req
                    self._publish(client, bundle, req)
                    return bundle
                last = getattr(rec, "last_bundle_path", None)
                if last and last != self._last_published \
                        and os.path.isdir(last):
                    self._publish(client, last, self._last_req_served)
                    return last
                return None
            except ConnectionError as e:
                # control plane degraded (StoreUnavailableError is a
                # ConnectionError; a failed DUMP — ENOSPC etc. — still
                # propagates): publications stay pending, re-tried once
                # the store answers again
                from . import get_telemetry

                get_telemetry().inc_counter(
                    "aggregator/degraded_ticks_total",
                    help="publisher beats skipped because the rendezvous "
                         "store was unreachable (publications buffered)")
                debug_once("aggregator/degraded_tick",
                           f"publisher tick degraded — store unreachable "
                           f"({e!r}); buffered for the next healthy beat")
                return None

    # -- worker-side daemon (subprocess deployments) -----------------------

    def start_daemon(self, endpoint: str,
                     interval_s: float = 1.0) -> None:
        """Drive :meth:`tick` from a daemon thread with this process's
        OWN store client.  This is how the publisher runs in subprocess
        deployments: ``entry.initialize`` executes in the WORKER process
        (which owns the flight recorder and ledger), while the elastic
        agent heartbeats in a different process — its ``get_publisher()``
        is None there.  Idempotent."""
        if self._daemon is not None:
            return
        from ..elasticity.rendezvous import RendezvousClient

        client = RendezvousClient(endpoint)
        self._daemon_stop.clear()

        def loop():
            while not self._daemon_stop.wait(interval_s):
                try:
                    self.tick(client)
                except Exception as e:
                    # store hiccup / dump failure; next beat retries
                    debug_once("aggregator/daemon_tick",
                               f"publisher daemon tick failed ({e!r})")

        self._daemon = threading.Thread(target=loop, daemon=True,
                                        name="ds-bundle-publisher")
        self._daemon.start()

    def stop_daemon(self) -> None:
        self._daemon_stop.set()
        t = self._daemon
        self._daemon = None
        if t is not None:
            t.join(timeout=2)


# ---------------------------------------------------------------------------
# collect side (rank 0 / operator)
# ---------------------------------------------------------------------------

def _heartbeat_view(client: Any, peer_ids: List[str]
                    ) -> Dict[str, Dict[str, Any]]:
    """Store-clock heartbeat ages + last payload per host at collect time
    (standalone twin of ``ElasticRendezvous.peer_heartbeat_ages`` — the
    collector may not be a rendezvous member)."""
    out: Dict[str, Dict[str, Any]] = {}
    try:
        now = client.now()
    except Exception:
        return out
    for pid in peer_ids:
        ts = client.get(f"rdzv/hb/{pid}")
        out[pid] = {
            "age_s": None if ts is None else round(now - float(ts), 3),
            "left": bool(client.get(f"rdzv/left/{pid}")),
            "info": client.get(f"rdzv/hbinfo/{pid}"),
        }
    return out


def _new_archive_dir(out_dir: str) -> str:
    """A fresh, never-colliding ``cluster-<utc>`` dir: a second-granular
    stamp alone merges two collects issued in the same second (scripted
    sweeps, retry loops), so disambiguate with an ``-NNN`` suffix when
    the plain name is taken."""
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
    base = os.path.join(out_dir, f"cluster-{stamp}")
    for i in range(1000):
        candidate = base if i == 0 else f"{base}-{i:03d}"
        try:
            os.makedirs(candidate, exist_ok=False)
            return candidate
        except FileExistsError:
            continue
    raise OSError(f"could not allocate an archive dir under {out_dir}")


def sealed_members(client: Any) -> List[str]:
    """The current round's frozen gang — the default peer set for a
    collect against a live rendezvous."""
    r = int(client.get("rdzv/round") or 0)
    sealed = client.get(f"rdzv/round/{r}/sealed")
    return list(sealed[0]) if sealed else []


def collect_cluster_archive(client: Any, peer_ids: Optional[List[str]] = None,
                            out_dir: str = "cluster_archives",
                            timeout_s: float = 30.0,
                            request: bool = True) -> str:
    """Assemble ONE operator-facing cluster archive from a live store.

    Bumps the collect-request counter (unless ``request=False`` — then
    whatever is already published is taken as-is), waits for every peer's
    publication meta to reach the new request id, pulls and unpacks each
    bundle, and writes the cluster manifest.  Hosts that never answer
    (dead, hung harder than their publisher thread) are recorded in the
    manifest as ``missing`` — absence at collect time is itself evidence.
    """
    peer_ids = list(peer_ids) if peer_ids else sealed_members(client)
    if not peer_ids:
        raise ValueError("collect: no peers (store has no sealed round; "
                         "pass peer ids explicitly)")
    req_id = int(client.add(_REQ_KEY, 1)) if request else 0
    archive = _new_archive_dir(out_dir)
    hosts_dir = os.path.join(archive, "hosts")
    os.makedirs(hosts_dir, exist_ok=True)

    def try_fetch(pid: str) -> Optional[str]:
        # one host's corrupt / mid-overwrite publication (chunks are
        # rewritten in place; we may race a re-publish) must not abort
        # the whole collect — that host retries or lands in `missing`,
        # which is itself evidence
        try:
            return fetch_bundle(client, pid, os.path.join(hosts_dir, pid))
        except Exception as e:
            logger.warning(f"aggregator: fetch from {pid} failed "
                           f"({e!r}); retrying / marking missing")
            return None

    deadline = time.monotonic() + float(timeout_s)
    pending = set(peer_ids)
    got: Dict[str, str] = {}
    while pending and time.monotonic() < deadline:
        for pid in sorted(pending):
            meta = client.get(_meta_key(pid))
            if isinstance(meta, dict) and int(meta.get("req", -1)) >= req_id:
                path = try_fetch(pid)
                if path:
                    got[pid] = path
                    pending.discard(pid)
        if pending:
            time.sleep(0.05)
    # a silent host may still have an OLDER publication (its last trip
    # bundle before it died) — better than nothing in the archive
    for pid in sorted(pending):
        path = try_fetch(pid)
        if path:
            got[pid] = path
    missing = sorted(set(peer_ids) - set(got))
    # PARTIAL publications (a hung host's heartbeat-thread last words —
    # ledger tail + stacks): persist each one next to its host's bundles;
    # for a MISSING host this is the only evidence in the archive
    partials: Dict[str, Any] = {}
    for pid in peer_ids:
        try:
            part = client.get(_partial_key(pid))
        except Exception:
            part = None
        if isinstance(part, dict):
            partials[pid] = {k: part.get(k) for k in
                             ("ts", "trips", "reason", "liveness")}
            try:
                pdir = os.path.join(hosts_dir, pid)
                os.makedirs(pdir, exist_ok=True)
                with open(os.path.join(pdir, "partial.json"), "w") as fh:
                    json.dump(part, fh, indent=2, default=str)
            except OSError as e:
                logger.warning(f"aggregator: partial for {pid} not "
                               f"persisted ({e!r})")
    build_cluster_manifest(archive,
                           heartbeat_ages=_heartbeat_view(client, peer_ids),
                           missing=missing, req_id=req_id,
                           partials=partials)
    try:
        # request-trace lanes (ISSUE 15): persist every node's current
        # request-record publication BEFORE the merged-trace build so
        # one build folds bundle spans and request lanes together
        collect_request_docs(client, archive)
    except (OSError, ConnectionError, ValueError) as e:
        logger.warning(f"aggregator: request-lane collect failed: {e!r}")
    try:
        # front-door access logs, rotated segments included — the
        # replayable record of what the fleet was actually asked to do
        collect_access_logs(client, archive)
    except (OSError, ConnectionError, ValueError) as e:
        logger.warning(f"aggregator: access-log collect failed: {e!r}")
    try:
        build_cluster_trace(archive)
    except Exception as e:  # the archive is still useful without it
        logger.warning(f"aggregator: cluster trace assembly failed: {e!r}")
    try:
        # the live rollup view at collect time: merged per-node-labeled
        # metrics straight from the store, next to the bundles
        from .rollup import collect_rollup

        collect_rollup(client, peer_ids).save(archive)
    except (OSError, ConnectionError, ValueError) as e:
        logger.warning(f"aggregator: rollup snapshot at collect failed: "
                       f"{e!r}")
    logger.error(f"aggregator: cluster archive written to {archive} "
                 f"({len(got)}/{len(peer_ids)} hosts"
                 + (f", missing {missing}" if missing else "") + ")")
    return archive


def collect_cluster_archive_fs(shared_fs_path: str,
                               out_dir: str = "cluster_archives") -> str:
    """Shared-filesystem collection: assemble an archive from whatever
    bundles hosts copied under ``<shared>/<node>/`` (the post-crash path
    — no live store required)."""
    import shutil

    nodes = sorted(d for d in os.listdir(shared_fs_path)
                   if os.path.isdir(os.path.join(shared_fs_path, d)))
    if not nodes:
        raise ValueError(f"collect: no host dirs under {shared_fs_path}")
    archive = _new_archive_dir(out_dir)
    for node in nodes:
        meta_p = os.path.join(shared_fs_path, node, "meta.json")
        bundle = None
        if os.path.exists(meta_p):
            with open(meta_p) as fh:
                bundle = json.load(fh).get("bundle")
        if bundle is None:  # fall back to the newest bundle dir
            cands = sorted(d for d in os.listdir(
                os.path.join(shared_fs_path, node)) if d.startswith("bundle"))
            bundle = cands[-1] if cands else None
        if bundle is None:
            continue
        src = os.path.join(shared_fs_path, node, bundle)
        dst = os.path.join(archive, "hosts", node, bundle)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copytree(src, dst)
    build_cluster_manifest(archive)
    try:
        build_cluster_trace(archive)
    except Exception as e:
        logger.warning(f"aggregator: cluster trace assembly failed: {e!r}")
    return archive


# ---------------------------------------------------------------------------
# cluster manifest
# ---------------------------------------------------------------------------

def load_host_manifests(archive: str) -> Dict[str, Dict[str, Any]]:
    """``{node_id: bundle manifest}`` from an archive's ``hosts/`` tree."""
    out: Dict[str, Dict[str, Any]] = {}
    hosts_dir = os.path.join(archive, "hosts")
    if not os.path.isdir(hosts_dir):
        return out
    for node in sorted(os.listdir(hosts_dir)):
        node_dir = os.path.join(hosts_dir, node)
        for bundle in sorted(os.listdir(node_dir)):
            mp = os.path.join(node_dir, bundle, BUNDLE_MANIFEST)
            if os.path.exists(mp):
                with open(mp) as fh:
                    out[node] = json.load(fh)
                break
    return out


def _ledger_tails(manifests: Dict[str, Dict[str, Any]]
                  ) -> Dict[str, List[Dict[str, Any]]]:
    tails = {}
    for node, m in manifests.items():
        led = (m.get("context") or {}).get("collective_ledger")
        if isinstance(led, dict) and isinstance(led.get("tail"), list):
            tails[node] = led["tail"]
    return tails


def build_cluster_manifest(archive: str,
                           heartbeat_ages: Optional[Dict[str, Any]] = None,
                           missing: Optional[List[str]] = None,
                           req_id: int = 0,
                           persist: bool = True,
                           partials: Optional[Dict[str, Any]] = None
                           ) -> Dict[str, Any]:
    """Fold every host bundle in ``archive`` into one manifest: per-host
    step index / reason / comm totals, cross-host step skew, comm-census
    deltas, and the collective-desync report.  Written to
    ``<archive>/cluster_manifest.json`` (unless ``persist=False`` — the
    read-only CLI path) and returned."""
    manifests = load_host_manifests(archive)
    hosts: Dict[str, Any] = {}
    census: Dict[str, Dict[str, float]] = {}
    for node, m in manifests.items():
        steps = m.get("steps") or []
        last = steps[-1] if steps else {}
        comm = m.get("comm") or {}
        led = (m.get("context") or {}).get("collective_ledger") or {}
        goodput = (m.get("context") or {}).get("goodput") or {}
        ct = (m.get("context") or {}).get("compile_programs") or {}
        mem = (m.get("context") or {}).get("memory") or {}
        mem_compact = None
        if mem:
            # per-host memory for the cluster view (telemetry/memory):
            # the full breakdown stays in the host bundle; the manifest
            # carries what an operator scans first
            mem_compact = {k: mem.get(k) for k in (
                "hbm_frac", "peak_hbm_bytes", "host_rss_bytes",
                "tracked_bytes", "device_unresponsive") if
                mem.get(k) is not None}
            from .memory.oom import top_pools_of

            top = top_pools_of(mem)
            if top:
                mem_compact["top_pools"] = top
        anat = (m.get("context") or {}).get("anatomy") or {}
        anat_compact = None
        if anat:
            # per-host step anatomy (ISSUE 17): the last capture's
            # comm/overlap fractions + the cost ledger's dominant
            # roofline verdict — enough to spot the comm-bound host
            # without opening its bundle
            cap = anat.get("last_capture") or {}
            anat_compact = {k: cap.get(k) for k in (
                "comm_fraction", "overlap_hiding_frac",
                "attributed_frac") if cap.get(k) is not None}
            top_v = (anat.get("cost_ledger") or {}).get("roofline_top")
            if top_v is not None:
                anat_compact["roofline_top"] = top_v
            anat_compact = anat_compact or None
        num = (m.get("context") or {}).get("numerics") or {}
        num_compact = None
        if num:
            # per-host tensor health (ISSUE 18): the last sampled (or
            # forensic) capture's worst-case scalars + the first
            # non-finite tensor name — the NaN-origin answer surfaces in
            # the cluster view without opening the host bundle
            summ = num.get("summary") or {}
            num_compact = {k: summ.get(k) for k in (
                "nonfinite_total", "underflow_frac", "saturated_frac",
                "layer_grad_max", "gate_entropy_frac", "moe_drop_rate")
                if summ.get(k) is not None}
            if num.get("first_nonfinite"):
                num_compact["first_nonfinite"] = num["first_nonfinite"]
            num_compact = num_compact or None
        hosts[node] = {
            "reason": m.get("reason"),
            "time_utc": m.get("time_utc"),
            "host": m.get("host"),
            "last_step": last.get("step"),
            "step_time_ms": last.get("step_time_ms"),
            "steps_recorded": len(steps),
            "health_events": len(m.get("health_events") or []),
            "comm_ops": comm.get("total_ops"),
            "comm_bytes": comm.get("total_bytes"),
            "ledger_seq": led.get("seq"),
            "ledger_tail_hash": led.get("tail_hash"),
            # per-host wall-clock budget (telemetry/perf): where this
            # host's time went, and how much of it was the compiler's
            "goodput": goodput.get("goodput"),
            "goodput_buckets_s": goodput.get("buckets_s"),
            "compile_events": ct.get("events_total"),
            "compile_time_ms": ct.get("time_ms_total"),
            "memory": mem_compact,
            "anatomy": anat_compact,
            "numerics": num_compact,
        }
        for op, e in (comm.get("summary") or {}).items():
            census.setdefault(op, {})[node] = float(e.get("count", 0))
    last_steps = [h["last_step"] for h in hosts.values()
                  if isinstance(h.get("last_step"), (int, float))]
    goodputs = [float(h["goodput"]) for h in hosts.values()
                if isinstance(h.get("goodput"), (int, float))]
    comm_delta = {
        op: {"per_host": by, "delta": max(by.values()) - min(by.values())}
        for op, by in sorted(census.items()) if len(by) >= 2}
    desync = find_first_divergence(_ledger_tails(manifests))
    manifest: Dict[str, Any] = {
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "collect_request": int(req_id),
        "hosts": hosts,
        "missing_hosts": list(missing or []),
        "partials": partials or {},
        "step_skew": (max(last_steps) - min(last_steps)
                      if len(last_steps) >= 2 else 0),
        "goodput_min": min(goodputs) if goodputs else None,
        "goodput_mean": (sum(goodputs) / len(goodputs)
                         if goodputs else None),
        "comm_census_delta": comm_delta,
        "heartbeat_ages": heartbeat_ages or {},
        "desync": desync,
        "desync_report": format_divergence_report(desync),
    }
    if persist:
        os.makedirs(archive, exist_ok=True)
        with open(os.path.join(archive, CLUSTER_MANIFEST), "w") as fh:
            json.dump(manifest, fh, indent=2, default=str)
    return manifest


# ---------------------------------------------------------------------------
# clock-aligned merged trace (ISSUE 13 tentpole)
# ---------------------------------------------------------------------------

def collect_request_docs(client: Any, archive: str) -> bool:
    """Persist every node's serving request-record publication
    (``telemetry/requests/<node>``) to ``<archive>/cluster_requests.
    json``; True when any node had one.  ``build_cluster_trace`` then
    folds them in as request lanes."""
    from ..serving.tracing import fetch_request_docs

    docs = fetch_request_docs(client)
    if not docs:
        return False
    path = os.path.join(archive, CLUSTER_REQUESTS)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"nodes": docs}, fh, default=str)
    os.replace(tmp, path)
    return True


ACCESSLOG_PREFIX = "telemetry/accesslog/"


def collect_access_logs(client: Any, archive: str) -> int:
    """Copy every registered front-door access log — the LIVE file AND
    its size-cap-rotated ``.1`` segment — into ``<archive>/access_logs/
    <node>/`` (ISSUE 16 satellite: the rotated segment holds the oldest
    retained traffic, so a replay built from the archive must see it).
    Doors register a path, not a stream (``telemetry/accesslog/
    <node>``); a path on another host's filesystem is recorded as a
    pointer (``remote.json``) instead of silently skipped.  Returns the
    number of log files copied."""
    import shutil

    copied = 0
    for key in sorted(client.keys(ACCESSLOG_PREFIX)):
        reg = client.get(key)
        if not isinstance(reg, dict) or not reg.get("path"):
            continue
        node = str(reg.get("node") or key[len(ACCESSLOG_PREFIX):])
        src = str(reg["path"])
        dst_dir = os.path.join(archive, "access_logs", node)
        segments = [p for p in (src + ".1", src) if os.path.exists(p)]
        if not segments:
            os.makedirs(dst_dir, exist_ok=True)
            with open(os.path.join(dst_dir, "remote.json"), "w") as fh:
                json.dump(reg, fh)
            continue
        os.makedirs(dst_dir, exist_ok=True)
        for seg in segments:
            base = "access.log" + (".1" if seg.endswith(".1") else "")
            try:
                shutil.copyfile(seg, os.path.join(dst_dir, base))
                copied += 1
            except OSError as e:
                logger.warning(f"aggregator: access log {seg} from "
                               f"{node} not copied ({e!r})")
    return copied


def _newest_bundle_trace(node_dir: str) -> Optional[str]:
    for bundle in sorted(os.listdir(node_dir), reverse=True):
        p = os.path.join(node_dir, bundle, "trace.json")
        if os.path.exists(p):
            return p
    return None


def build_cluster_trace(archive: str, persist: bool = True
                        ) -> Optional[Dict[str, Any]]:
    """Merge every host bundle's ``trace.json`` into ONE Chrome-trace
    document with clock-aligned per-process lanes.

    Each tracer exports ``metadata.clock_sync.trace_to_store_offset_us``
    (``telemetry/clocksync.py``): adding it to a span's ``ts`` lands the
    span on the shared store clock.  Hosts are remapped onto distinct
    ``pid`` lanes (with ``process_name`` metadata events so Perfetto
    labels them by node id), aligned timestamps are re-based to the
    earliest aligned span across the gang, and hosts WITHOUT a clock
    sync are still included — flagged ``aligned: false`` and left on
    their private timebase (re-based to zero) rather than dropped.  The
    result is what makes a store outage or a straggler legible as
    aligned slices across processes."""
    hosts_dir = os.path.join(archive, "hosts")
    lanes: Dict[str, Dict[str, Any]] = {}
    for node in (sorted(os.listdir(hosts_dir))
                 if os.path.isdir(hosts_dir) else []):
        node_dir = os.path.join(hosts_dir, node)
        if not os.path.isdir(node_dir):
            continue
        tp = _newest_bundle_trace(node_dir)
        if tp is None:
            continue
        try:
            with open(tp) as fh:
                trace = json.load(fh)
        except (OSError, ValueError) as e:
            logger.warning(f"aggregator: unreadable trace for {node} "
                           f"({e!r}); lane skipped")
            continue
        meta = trace.get("metadata") or {}
        sync = meta.get("clock_sync") or {}
        off_us = sync.get("trace_to_store_offset_us")
        events = [e for e in (trace.get("traceEvents") or [])
                  if isinstance(e.get("ts"), (int, float))]
        lanes[node] = {
            "events": events,
            "aligned": isinstance(off_us, (int, float)),
            "offset_us": float(off_us) if isinstance(
                off_us, (int, float)) else 0.0,
            "clock_sync": sync or None,
        }
    # serving request lanes (ISSUE 15): per-node request-record docs
    # persisted by collect_request_docs — same store clock, so they
    # share the merged timeline's base
    req_docs: Dict[str, Dict[str, Any]] = {}
    req_path = os.path.join(archive, CLUSTER_REQUESTS)
    if os.path.exists(req_path):
        try:
            with open(req_path) as fh:
                req_docs = {
                    str(n): d for n, d in
                    (json.load(fh).get("nodes") or {}).items()
                    if isinstance(d, dict)}
        except (OSError, ValueError) as e:
            logger.warning(f"aggregator: unreadable {CLUSTER_REQUESTS} "
                           f"({e!r}); request lanes skipped")
    # fleet profiler device lanes (ISSUE 20): per-node capture
    # publications persisted under ``profiles/<node>/device_events.json``
    # — MEASURED device-op spans, anchored to the store clock at capture
    # start, merged as their own pid lanes next to the host spans
    from .profiler.fleet import load_profiles

    profiles = load_profiles(archive)
    if not lanes and not req_docs and not profiles:
        return None
    aligned_starts = [ev["ts"] + lane["offset_us"]
                      for lane in lanes.values() if lane["aligned"]
                      for ev in lane["events"]]
    for doc in profiles.values():
        clock = doc.get("clock") or {}
        if clock.get("aligned") and isinstance(clock.get("store_t0_s"),
                                               (int, float)):
            aligned_starts.append(float(clock["store_t0_s"]) * 1e6)
    for doc in req_docs.values():
        clock = doc.get("clock") or {}
        if clock.get("synced") and isinstance(clock.get("offset_s"),
                                              (int, float)):
            aligned_starts.extend(
                (float(r["start_ts"]) + float(clock["offset_s"])) * 1e6
                for r in doc.get("records") or []
                if isinstance(r, dict)
                and isinstance(r.get("start_ts"), (int, float)))
    base_us = min(aligned_starts) if aligned_starts else 0.0
    out_events: List[Dict[str, Any]] = []
    hosts_meta: Dict[str, Any] = {}
    for pid, node in enumerate(sorted(lanes)):
        lane = lanes[node]
        out_events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "args": {"name": node + (
                               "" if lane["aligned"] else " (unaligned)")}})
        lane_min = min((ev["ts"] for ev in lane["events"]), default=0.0)
        for ev in lane["events"]:
            ev = dict(ev)
            if lane["aligned"]:
                ev["ts"] = round(ev["ts"] + lane["offset_us"] - base_us, 1)
            else:
                # no clock sync: keep internal order, re-based to zero
                ev["ts"] = round(ev["ts"] - lane_min, 1)
            ev["pid"] = pid
            out_events.append(ev)
        hosts_meta[node] = {
            "pid": pid, "aligned": lane["aligned"],
            "events": len(lane["events"]),
            "clock_sync": lane["clock_sync"],
        }
    next_pid = len(lanes)
    if req_docs:
        from ..serving.tracing import request_trace_events

        for node in sorted(req_docs):
            evs, aligned = request_trace_events(
                node, req_docs[node], next_pid, base_us=base_us)
            out_events.extend(evs)
            hosts_meta[f"{node} (requests)"] = {
                "pid": next_pid, "aligned": aligned,
                "events": len(evs) - 1, "requests": True}
            next_pid += 1
    for node in sorted(profiles):
        doc = profiles[node]
        clock = doc.get("clock") or {}
        aligned = bool(clock.get("aligned")
                       and isinstance(clock.get("store_t0_s"),
                                      (int, float)))
        events = [e for e in (doc.get("events") or [])
                  if isinstance(e, dict)
                  and isinstance(e.get("ts_us"), (int, float))]
        out_events.append({
            "ph": "M", "name": "process_name", "pid": next_pid,
            "args": {"name": f"{node} (device)"
                     + ("" if aligned else " (unaligned)")}})
        lane_names = sorted({str(e.get("lane", "")) for e in events})
        tids = {ln: i for i, ln in enumerate(lane_names)}
        for ln, tid in tids.items():
            out_events.append({"ph": "M", "name": "thread_name",
                               "pid": next_pid, "tid": tid,
                               "args": {"name": ln or "device"}})
        lane_min = min((float(e["ts_us"]) for e in events), default=0.0)
        # the profiler trace's timestamps are session-local: pin the
        # lane's first event at the capture's store-clock anchor, keep
        # intra-lane offsets exact
        anchor_us = (float(clock["store_t0_s"]) * 1e6 - base_us
                     if aligned else 0.0)
        for e in events:
            out_events.append({
                "ph": "X", "name": str(e.get("name", "?")),
                "pid": next_pid, "tid": tids.get(str(e.get("lane", "")), 0),
                "ts": round(float(e["ts_us"]) - lane_min + anchor_us, 1),
                "dur": round(float(e.get("dur_us", 0.0)), 1),
                "cat": "device"})
        hosts_meta[f"{node} (device)"] = {
            "pid": next_pid, "aligned": aligned,
            "events": len(events), "device": True,
            "device_kind": doc.get("device_kind"),
            "req": doc.get("req"), "clock": clock or None}
        next_pid += 1
    doc = {"traceEvents": out_events,
           "displayTimeUnit": "ms",
           "metadata": {"source": "deepspeed_tpu.telemetry.aggregator",
                        "store_clock_base_us": base_us,
                        "hosts": hosts_meta}}
    if persist:
        path = os.path.join(archive, CLUSTER_TRACE)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
    return doc


# ---------------------------------------------------------------------------
# live desync check (rank 0's heartbeat loop)
# ---------------------------------------------------------------------------

def check_desync_live(client: Any, peer_ids: List[str]) -> Optional[dict]:
    """Rank 0, every heartbeat tick: compare the ``coll_seq``/``coll_hash``
    riding each peer's heartbeat payload.  Publishes
    ``elastic/collective_seq_skew`` and, on a desync, bumps
    ``elastic/collective_desync_events`` and annotates the local flight
    recorder (the NEXT bundle then says when rank 0 first saw it)."""
    from .collective_ledger import desync_from_heartbeats

    payloads = {pid: client.get(f"rdzv/hbinfo/{pid}") for pid in peer_ids}
    report = desync_from_heartbeats(payloads)
    if report is None:
        return None
    from . import get_telemetry

    tel = get_telemetry()
    tel.set_gauge("elastic/collective_seq_skew", report["seq_skew"],
                  help="max-min collective ledger seq across the gang")
    if report.get("desync"):
        tel.inc_counter(
            "elastic/collective_desync_events",
            help="heartbeat ledger hashes disagreed at the same seq")
        from .flight_recorder import get_flight_recorder

        get_flight_recorder().annotate("collective_desync", report)
        logger.error(f"aggregator: live collective desync detected: "
                     f"{report.get('mismatch')}")
    return report


# ---------------------------------------------------------------------------
# process-global publisher + config wiring
# ---------------------------------------------------------------------------

_publisher: Optional[BundlePublisher] = None


def get_publisher() -> Optional[BundlePublisher]:
    """The installed publisher, if any — the elastic agent drives its
    ``tick`` from the heartbeat loop."""
    return _publisher


def set_publisher(pub: Optional[BundlePublisher]) -> None:
    global _publisher
    prev = _publisher
    _publisher = pub
    if prev is not None and prev is not pub:
        prev.stop_daemon()  # a replaced publisher must not leak its thread


def publisher_from_config(tcfg: Any, node_id: Optional[str] = None
                          ) -> Optional[BundlePublisher]:
    """Resolve the ``telemetry.aggregation`` config sub-group into the
    installed process-global publisher (None when disabled).  Also None
    when the flight recorder is disabled by config — the publisher's
    whole job is dumping and shipping bundles, and 'the operator said
    no' to bundles must not be bypassed through the global recorder."""
    agg = tcfg.aggregation
    if not agg.enabled:
        set_publisher(None)
        return None
    from .flight_recorder import recorder_from_config

    recorder = recorder_from_config(tcfg)
    if recorder is None:
        logger.warning("telemetry.aggregation enabled but the flight "
                       "recorder is disabled — no bundles to publish; "
                       "publisher not installed")
        set_publisher(None)
        return None
    pub = BundlePublisher(
        node_id=node_id or os.environ.get("DS_ELASTIC_NODE_ID",
                                          f"node-{os.getpid()}"),
        recorder=recorder,
        chunk_bytes=agg.chunk_bytes,
        max_bundle_bytes=agg.max_bundle_bytes,
        shared_fs_path=agg.shared_fs_path,
        telemetry_push_every_s=(
            float(getattr(agg, "metrics_push_every_s", 2.0))
            if getattr(agg, "metrics_rollup", True) else 0.0))
    set_publisher(pub)
    return pub
