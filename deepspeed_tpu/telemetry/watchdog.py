"""Hang / straggler watchdog over train-step progress notifications.

MegaScale (arXiv:2402.15627) attributes most of its >90% effective
training time to automated hang diagnosis; the failure mode it targets —
a collective that never completes, a host that silently stalls — leaves
NO error anywhere, just a process that stops making progress.  This
watchdog is that detector for this runtime:

* the engine calls :meth:`HangWatchdog.notify_progress` after every
  completed ``train_step`` (step index + step time, folded into an EWMA);
* a daemon thread (or an explicit :meth:`check` call — the tests drive a
  **fake clock** through it, no sleeps) compares the injectable clock
  against the last progress stamp;
* ``comms_logger`` activity is a secondary liveness signal: a long
  compile or a giant eager collective moves comm counters without
  finishing a step, and must not be declared a hang;
* on trip it dumps a flight-recorder debug bundle (last spans,
  StepRecords, per-thread stacks, peer heartbeat ages) and runs the
  configured action: ``log`` (keep running), ``raise``
  (:class:`WatchdogTimeout` — from the daemon thread this interrupts the
  main thread), or ``exit`` (``os._exit(2)`` for supervisors that
  restart on death, e.g. the elastic agent).

The per-host :meth:`heartbeat_payload` (step index, step-time EWMA,
progress age) is what the elastic agent folds into its rendezvous
heartbeat so rank 0 can publish straggler-skew gauges across hosts.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..utils.logging import debug_once, logger

ACTIONS = ("log", "raise", "exit")

#: heartbeat-payload schema version (satellite, ISSUE 13).  The payload
#: accreted step/EWMA/goodput/coll_seq/hbm fields across PRs 2-7 with no
#: version and no size bound; consumers (rank 0's straggler publisher,
#: the rollup, `telemetry top`) now key behavior on ``v`` instead of
#: sniffing fields, and producers cap the byte size below.
HEARTBEAT_SCHEMA_V = 1

#: default byte cap for heartbeat payloads — the watchdog ctor default
#: AND the cap producers without a watchdog config (the agent's
#: ledger-only path) apply, so the bound is defined exactly once
DEFAULT_HEARTBEAT_MAX_BYTES = 1024

#: deterministic field-drop order under the byte cap: least
#: operator-critical first.  ``v`` and ``step`` are never dropped (the
#: version is what makes the drop legible downstream; the step index is
#: the minimum liveness signal every consumer needs).  Fields NOT in
#: this order (a future producer's additions) drop before everything
#: listed, in sorted-name order — deterministic by construction.
HEARTBEAT_DROP_ORDER = (
    "goodput_total",    # the rolling figure is the live one
    "hbm_headroom",
    "hbm_frac",
    "goodput",
    "progress_age_s",   # derivable from the store-stamped hb age
    "coll_hash",        # desync detection degrades to seq-skew only
    "coll_seq",
    "step_time_ewma_ms",
)


def cap_heartbeat_payload(payload: Dict[str, Any],
                          max_bytes: int) -> Dict[str, Any]:
    """Bound a heartbeat payload's JSON size by dropping fields in
    :data:`HEARTBEAT_DROP_ORDER` (unknown fields first).  Dropped
    fields are counted (``elastic/heartbeat_fields_dropped_total``) and
    the payload records how many went missing (``dropped``) so the
    consumer can tell 'field absent' from 'field capped'."""
    import json as _json

    if max_bytes <= 0:
        return payload
    payload = dict(payload)
    payload.setdefault("v", HEARTBEAT_SCHEMA_V)

    def size() -> int:
        return len(_json.dumps(payload, default=str))

    if size() <= max_bytes:
        return payload
    protected = ("v", "step", "dropped")
    known = [f for f in HEARTBEAT_DROP_ORDER if f in payload]
    unknown = sorted(f for f in payload
                     if f not in HEARTBEAT_DROP_ORDER
                     and f not in protected)
    dropped = 0
    for field in unknown + known:
        if size() <= max_bytes:
            break
        payload.pop(field, None)
        dropped += 1
        payload["dropped"] = dropped
    if dropped:
        try:
            from . import get_telemetry

            get_telemetry().inc_counter(
                "elastic/heartbeat_fields_dropped_total", v=dropped,
                help="heartbeat payload fields dropped by the byte cap")
        except Exception as e:  # counter publish is best-effort
            debug_once("watchdog/hb_cap_counter",
                       f"heartbeat-cap counter publish failed ({e!r})")
        debug_once("watchdog/hb_cap",
                   f"heartbeat payload over {max_bytes}B — dropped "
                   f"{dropped} field(s) (deterministic order; see "
                   f"HEARTBEAT_DROP_ORDER)")
    return payload


class WatchdogTimeout(RuntimeError):
    """No train-step progress within ``hang_timeout_s``."""


class HangWatchdog:
    #: default ``recorder``: resolve the process-global flight recorder
    #: at trip time.  Pass an explicit ``None`` to trip WITHOUT dumping
    #: (the engine does when ``telemetry.flight_recorder`` is disabled).
    GLOBAL_RECORDER = object()

    def __init__(self, hang_timeout_s: float = 300.0,
                 poll_interval_s: float = 0.0,
                 action: str = "log",
                 comm_liveness: bool = True,
                 clock: Callable[[], float] = time.monotonic,
                 recorder: Any = GLOBAL_RECORDER,
                 device_probe: bool = True,
                 device_probe_timeout_s: float = 20.0,
                 heartbeat_max_bytes: int = DEFAULT_HEARTBEAT_MAX_BYTES):
        if action not in ACTIONS:
            raise ValueError(f"watchdog action {action!r} not in {ACTIONS}")
        self.hang_timeout_s = float(hang_timeout_s)
        #: 0 → a quarter of the timeout, capped at 10s (fast enough to
        #: catch a hang within ~1.25x the configured budget)
        self.poll_interval_s = (float(poll_interval_s) if poll_interval_s
                                else min(self.hang_timeout_s / 4.0, 10.0))
        self.action = action
        self.comm_liveness = bool(comm_liveness)
        #: bounded device-liveness check on the trip path (ISSUE 7): a
        #: dead TPU tunnel hangs jax.devices() INDEFINITELY (BENCH_r05:
        #: 180 s+), and the bundle dump's memory providers would walk
        #: straight into that hang — probe first, latch the verdict,
        #: annotate the bundle with ``device_unresponsive``
        self.device_probe = bool(device_probe)
        self.device_probe_timeout_s = float(device_probe_timeout_s)
        #: byte cap on heartbeat_payload (<= 0 disables): the payload
        #: rides every rendezvous heartbeat — an unbounded dict would
        #: let one noisy producer bloat every store beat in the gang
        self.heartbeat_max_bytes = int(heartbeat_max_bytes)
        #: test seam: injectable probe body (a hanging fake backend)
        self.device_probe_fn: Optional[Callable[[], Any]] = None
        self._clock = clock
        self._recorder = recorder
        self._lock = threading.Lock()
        self._last_progress = self._clock()
        self._last_step = -1
        self._ewma_ms = 0.0
        self._last_comm_ops = self._comm_ops()
        self._tripped = False
        self.trips = 0
        #: fns called on every trip edge with (reason, bundle_path_or_None)
        #: — the resilience policy's emergency-save subscribes here; ran
        #: BEFORE the configured action (an action="exit" must not skip
        #: the emergency flush), each guarded so one listener's failure
        #: cannot mask another's
        self._trip_listeners: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_trip_listener(self, fn: Callable[[str, Optional[str]], Any]
                          ) -> None:
        self._trip_listeners.append(fn)

    def remove_trip_listener(self, fn: Callable[[str, Optional[str]], Any]
                             ) -> None:
        """Detach a listener added with :meth:`add_trip_listener` (no-op
        if absent) — listeners are strong references, so a subscriber
        with a bounded lifetime must detach to be collectable."""
        try:
            self._trip_listeners.remove(fn)
        except ValueError:
            pass  # already removed / never added: detach is idempotent

    # -- progress feed (engine hot path: one lock + a few floats) ----------

    def notify_progress(self, step: int,
                        step_time_s: Optional[float] = None) -> None:
        with self._lock:
            self._last_progress = self._clock()
            self._last_step = int(step)
            if step_time_s is not None:
                ms = float(step_time_s) * 1e3
                self._ewma_ms = (ms if self._ewma_ms == 0.0
                                 else 0.9 * self._ewma_ms + 0.1 * ms)
            self._tripped = False  # re-arm: progress resumed

    def heartbeat_payload(self) -> Dict[str, float]:
        """Per-host liveness summary for the rendezvous heartbeat: rank 0
        folds every peer's payload into straggler-skew gauges.  When the
        collective ledger is on, its ``coll_seq``/``coll_hash`` ride
        along so rank 0 can detect collective desync live."""
        with self._lock:
            payload = {"v": HEARTBEAT_SCHEMA_V,
                       "step": self._last_step,
                       "step_time_ewma_ms": round(self._ewma_ms, 3),
                       "progress_age_s": round(
                           self._clock() - self._last_progress, 3)}
        from .collective_ledger import get_collective_ledger

        led = get_collective_ledger()
        if led.enabled:
            payload.update(led.heartbeat_summary())
        from .perf.goodput import get_goodput_ledger

        gp = get_goodput_ledger()
        if gp.enabled:
            # rolling goodput rides the heartbeat: rank 0 folds every
            # host's fraction into cluster gauges
            # (rendezvous.publish_straggler_stats)
            payload.update(gp.heartbeat_summary())
        from .memory import get_memory_ledger

        mem = get_memory_ledger()
        if mem.enabled:
            # HBM high-water + headroom ride along: rank 0 publishes
            # elastic/cluster_hbm_{max,headroom_min} and the cluster
            # manifest shows per-host memory
            payload.update(mem.heartbeat_summary())
        return cap_heartbeat_payload(payload, self.heartbeat_max_bytes)

    # -- the check ---------------------------------------------------------

    def _comm_ops(self) -> int:
        try:
            from ..comm.comm import comms_logger

            ops = comms_logger.total_ops()
            for e in comms_logger.exec_stats.values():
                ops += int(e.get("count", 0))
            return ops
        except Exception:
            return 0

    def check(self) -> bool:
        """One watchdog tick against the injected clock.  Returns True if
        this call tripped; the configured action runs on the trip edge
        only (re-armed by the next :meth:`notify_progress`)."""
        now = self._clock()
        if self.comm_liveness:
            ops = self._comm_ops()
            with self._lock:
                if ops != self._last_comm_ops:
                    # collectives are still flowing — a long compile or a
                    # giant eager gather is slow, not hung
                    self._last_comm_ops = ops
                    self._last_progress = now
        with self._lock:
            age = now - self._last_progress
            if age <= self.hang_timeout_s or self._tripped:
                return False
            self._tripped = True
            step, ewma = self._last_step, self._ewma_ms
        self._trip(age, step, ewma)
        return True

    def _trip(self, age: float, step: int, ewma_ms: float) -> None:
        reason = (f"watchdog: no train_step progress for {age:.1f}s "
                  f"(hang_timeout_s={self.hang_timeout_s}, last step "
                  f"{step}, step-time EWMA {ewma_ms:.1f}ms)")
        try:
            from .perf.goodput import get_goodput_ledger

            # the no-progress interval is detected stall time: charge it
            # so cluster goodput reflects the hang even if the process
            # survives (action="log")
            get_goodput_ledger().add("stall", age)
        except Exception as e:  # accounting is optional mid-incident
            debug_once("watchdog/stall_charge",
                       f"stall goodput charge failed ({e!r})")
        probe = None
        if self.device_probe:
            try:
                from .memory.ledger import probe_device_liveness

                probe = probe_device_liveness(
                    self.device_probe_timeout_s,
                    probe_fn=self.device_probe_fn)
                if probe.get("timed_out"):
                    # fail-fast verdict INSTEAD of the 180 s+ hang: the
                    # latch probe_device_liveness set makes every memory
                    # provider in the dump below skip the device.  Only
                    # a TIMEOUT is "unresponsive" — a probe the runtime
                    # ANSWERED with an error is responsive-but-unhealthy
                    # and must not send the operator down the dead-
                    # tunnel path (the probe result still rides extra)
                    reason += (f" [device unresponsive: "
                               f"{probe.get('detail')}]")
            except Exception as e:  # the dump itself matters more
                debug_once("watchdog/device_probe",
                           f"device-liveness probe failed ({e!r})")
        bundle = None
        recorder = self._recorder
        if recorder is HangWatchdog.GLOBAL_RECORDER:
            from .flight_recorder import get_flight_recorder

            recorder = get_flight_recorder()
        if recorder is not None:  # None = flight recorder disabled
            extra = {"last_step": step, "step_time_ewma_ms": ewma_ms,
                     "progress_age_s": age}
            if probe is not None:
                extra["device_probe"] = probe
                if probe.get("timed_out"):
                    extra["device_unresponsive"] = True
            try:
                from .collective_ledger import get_collective_ledger

                led = get_collective_ledger()
                if led.enabled:
                    # the hang headline names the last collective this
                    # rank issued — the first thing a desync post-mortem
                    # compares across hosts
                    extra.update(led.heartbeat_summary())
            except Exception as e:  # the dump itself matters more
                debug_once("watchdog/ledger_summary",
                           f"ledger summary for trip bundle failed "
                           f"({e!r})")
            try:
                bundle = recorder.dump(reason, extra=extra)
            except Exception as e:
                logger.error(f"watchdog: bundle dump failed: {e!r}")
        for listener in list(self._trip_listeners):
            try:
                listener(reason, bundle)
            except Exception as e:
                logger.error(f"watchdog: trip listener failed: {e!r}")
        # bump AFTER the dump: a monitor polling `trips` may read the
        # bundle path the moment the counter moves
        self.trips += 1
        try:
            from . import get_telemetry

            get_telemetry().inc_counter(
                "watchdog/trips", help="hang watchdog trips")
        except Exception as e:  # counter publish is best-effort
            debug_once("watchdog/trip_counter",
                       f"trip counter publish failed ({e!r})")
        msg = f"{reason}; debug bundle: {bundle}"
        if self.action == "exit":
            logger.error(msg + " — exiting (watchdog action=exit)")
            os._exit(2)
        if self.action == "raise":
            raise WatchdogTimeout(msg)
        logger.error(msg)

    # -- daemon thread -----------------------------------------------------

    @property
    def started(self) -> bool:
        return self._thread is not None

    def start(self) -> None:
        """Idempotent: spawn the daemon poll thread (real clock mode)."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ds-hang-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check()
            except WatchdogTimeout as e:
                # action="raise" from the daemon thread: the exception
                # cannot cross threads, so interrupt the main thread (a
                # KeyboardInterrupt at its next bytecode boundary) after
                # logging — a hung COLLECTIVE won't be interruptible, but
                # the bundle is already on disk either way
                logger.error(f"watchdog: {e}")
                import _thread

                _thread.interrupt_main()
                return
            except Exception as e:
                logger.warning(f"watchdog check failed: {e!r}")


_watchdog: Optional[HangWatchdog] = None


def get_watchdog() -> Optional[HangWatchdog]:
    """The process-global watchdog, if one was installed (the elastic
    agent reads it to fold progress into rendezvous heartbeats)."""
    return _watchdog


def set_watchdog(wd: Optional[HangWatchdog]) -> None:
    global _watchdog
    _watchdog = wd
