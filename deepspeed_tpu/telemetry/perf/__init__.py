"""Performance observability plane (ISSUE 5).

Three cooperating pieces that turn "it trains" observability into "it
trains at the speed the hardware allows" observability:

* :mod:`.compile_tracker` — ``tracked_jit`` at every engine jit site:
  per-program compile events with structured recompile-cause diffs,
  compile counters/gauges, a per-site program table in debug bundles.
* :mod:`.goodput` — the wall-clock account: productive / compile /
  stall / recovery / checkpoint buckets fed by the engine, the
  resilience policy, the watchdog, and the checkpoint engine; the
  rolling fraction rides watchdog heartbeats cluster-wide.
* :mod:`.baseline` — the perf-regression sentinel behind
  ``python -m deepspeed_tpu.telemetry perf {show,baseline,check}``
  (exit 3 on regression vs the stored baseline).
"""

from .baseline import (ABS_FLOORS, DEFAULT_BASELINE, PERF_METRICS,
                       check_regression, environment_failure_reason,
                       extract_perf, format_check_report, load_baseline,
                       load_run, parse_tolerances, save_baseline)
from .compile_tracker import (CompileEvent, CompileTracker,
                              configure_compile_tracker, diff_signatures,
                              get_compile_tracker, signature_of, tracked_jit)
from .goodput import (BUCKETS, GoodputLedger, configure_goodput_ledger,
                      get_goodput_ledger)

__all__ = [
    "CompileEvent", "CompileTracker", "configure_compile_tracker",
    "get_compile_tracker", "tracked_jit", "signature_of", "diff_signatures",
    "GoodputLedger", "configure_goodput_ledger", "get_goodput_ledger",
    "BUCKETS",
    "PERF_METRICS", "ABS_FLOORS", "DEFAULT_BASELINE", "load_run",
    "extract_perf", "save_baseline", "load_baseline", "check_regression",
    "format_check_report", "parse_tolerances",
    "environment_failure_reason",
]
