"""Perf-regression sentinel — baseline persistence + tolerance check.

``BENCH_r*.json`` has been a *log*: every round appends a number, nobody
is forced to look when it drifts down.  This module makes it a *gated
trajectory*: ``bench.py`` persists a perf baseline (step-time p50, MFU,
compile seconds, goodput, tokens/sec) and
``python -m deepspeed_tpu.telemetry perf {show,baseline,check}``
compares any later run against it, exiting **3** on regression beyond
configurable tolerances — the same scriptable-exit-code contract as the
``desync`` command.

A *run file* is a bench JSON line (the object ``bench.py`` prints), a
driver ``BENCH_r*.json`` artifact (the same object under ``"parsed"``),
or a previously saved baseline file — all three carry the same metric
keys at top level or under ``metrics``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

#: metric -> (direction, default relative tolerance).  "higher" means
#: higher is better (a drop beyond tol regresses); "lower" the reverse.
PERF_METRICS: Dict[str, Tuple[str, float]] = {
    "tokens_per_sec": ("higher", 0.10),
    "mfu": ("higher", 0.10),
    "goodput": ("higher", 0.05),
    "step_time_p50_ms": ("lower", 0.10),
    "compile_time_s": ("lower", 0.25),
    # memory plane (telemetry/memory): the same config suddenly holding
    # more HBM is a regression long before it is an OOM
    "peak_hbm_bytes": ("lower", 0.10),
    "hbm_headroom_frac": ("higher", 0.10),
    # tuning plane (deepspeed_tpu/tuning): the best-known-config path —
    # the MFU the headline model reaches UNDER the stored tuned config.
    # Gated so a store regression (a bad promotion, a stale entry) shows
    # up in the trajectory exactly like a code regression.
    "tuned_mfu": ("higher", 0.10),
    # serving plane (deepspeed_tpu/serving): the multi-tenant SLO gate —
    # interactive tail latency, shared-prefix effectiveness, per-class
    # goodput.  TTFT tails are noisier than throughput medians, hence
    # the wider tolerance + absolute floor.
    "serving_p99_ttft_ms": ("lower", 0.25),
    "prefix_hit_rate": ("higher", 0.10),
    "tok_s_interactive": ("higher", 0.15),
    "tok_s_background": ("higher", 0.25),
    # kernel plane (ops/pallas — ISSUE 12): no kernel ships without a
    # number.  Speedups are ratios vs the XLA reference ladder rung the
    # dispatch would otherwise take; the fused-adam figure is effective
    # HBM GB/s over the 7-floats/param logical traffic (same accounting
    # as optax_adam_hbm_gbps so the two compare); hiding_frac is the
    # share of collective time the ring decomposition buries under
    # compute.  A drop beyond tolerance exits 3 like any other metric.
    "flash_speedup_s2048": ("higher", 0.10),
    "flash_speedup_s8192": ("higher", 0.10),
    "flash_speedup_s32768": ("higher", 0.10),
    "block_sparse_speedup_s4096": ("higher", 0.10),
    "fused_adam_hbm_gbps": ("higher", 0.15),
    "overlap_hiding_frac": ("higher", 0.15),
    # anatomy plane (ISSUE 17): the trace-measured exposed-collective
    # share of step wall time.  LOWER is better — a rise means formerly
    # hidden (or absent) collective time is now serializing the step.
    # Gated one-sided like every metric: absent from an older baseline
    # → SKIPPED, never a fail.
    "comm_fraction": ("lower", 0.25),
    # network serving plane (ISSUE 14): the same SLO gate measured
    # through the REAL stack — HTTP/SSE front door + replica worker
    # processes.  Socket + process scheduling jitter is wider than the
    # in-process path, hence the looser tolerances + TTFT abs floor.
    "serving_net_p99_ttft_ms": ("lower", 0.30),
    "serving_net_qps_sustained": ("higher", 0.25),
    "serving_net_prefix_hit_rate": ("higher", 0.10),
    # SLO control plane (ISSUE 16): the worst slow-window burn rate
    # across the latency objectives during the replay workload.  A
    # burn < 1.0 means the error budget outlives the window, so the
    # signal is only meaningful near/above 1.0 — wide tolerance (burn
    # is a ratio of tail latencies, double jitter) plus an absolute
    # floor below which changes are error-budget noise.
    "serving_slo_burn_rate_p99": ("lower", 0.50),
    # numerics plane (ISSUE 18): fractional step-time cost of running the
    # sampled probes-on step variant vs the base step on the same
    # problem.  LOWER is better — the plane's whole contract is "stats
    # ride the step for (nearly) free"; a rise means a probe started
    # forcing a host sync or broke an XLA fusion.
    "numerics_overhead_frac": ("lower", 0.50),
    # expert-parallel plane (ISSUE 19): the Mixtral proxy trained with
    # the expert mesh axis > 1.  tokens/sec gates the whole ep pipeline
    # (sharded experts + sparse dispatch + ZeRO over (expert, data));
    # dispatch_speedup is the index-form dispatch vs the dense [T,E,C]
    # einsum on the same routing (sub-1.0 = the crossover auto-dispatch
    # regressed); drop_rate is the capacity-dropped token fraction at
    # the bench's fixed capacity factor — a rise means routing skew or
    # a capacity/padding regression, long before loss curves show it.
    "moe_ep_tokens_per_sec": ("higher", 0.15),
    "moe_dispatch_speedup": ("higher", 0.15),
    "moe_drop_rate": ("lower", 0.25),
    # fleet profiler plane (ISSUE 20): percent step-time cost of the
    # duty-cycled continuous capture (duty-cycle on vs off over the same
    # fenced steps).  LOWER is better — always-on capture only earns its
    # keep with a bounded overhead budget; a rise means the trace
    # stop/parse/census machinery started eating the step loop.  Wide
    # tolerance: the number is a ratio of two small wall times.
    "profiler_overhead_pct": ("lower", 0.50),
}

#: ignore regressions on metrics whose baseline is this close to zero —
#: a 0.001s compile baseline must not flag a 0.002s run
ABS_FLOORS: Dict[str, float] = {
    "compile_time_s": 1.0,
    "step_time_p50_ms": 1.0,
    # sub-64MiB HBM jitter (allocator rounding, cache growth) is noise
    "peak_hbm_bytes": 64 * 1024 * 1024,
    # sub-50ms TTFT jitter is dispatch noise on a tunneled chip
    "serving_p99_ttft_ms": 50.0,
    # the network tail additionally rides loopback + SSE write jitter
    "serving_net_p99_ttft_ms": 75.0,
    # a fleet comfortably inside its SLO burns < 0.25 of budget-rate;
    # movement below that is noise, not a regression
    "serving_slo_burn_rate_p99": 0.25,
    # a step whose exposed-collective share is under 5% is effectively
    # compute-bound; scheduler jitter down there is not a regression
    "comm_fraction": 0.05,
    # ISSUE 18 acceptance ceiling: probe overhead under 5% of step time
    # is sampling noise on a tunneled chip, not a regression
    "numerics_overhead_frac": 0.05,
    # a top-2 router dropping under 2% of tokens is routing jitter at
    # the bench's capacity factor, not a capacity regression
    "moe_drop_rate": 0.02,
    # capture overhead under 5% of step time is scheduler noise on a
    # CPU-backend bench, not a profiler regression
    "profiler_overhead_pct": 5.0,
}

DEFAULT_BASELINE = "PERF_BASELINE.json"


def load_run(path: str) -> Dict[str, Any]:
    """Load a run file and normalize to a flat dict of values."""
    with open(path) as fh:
        data = json.load(fh)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]  # driver BENCH_r*.json artifact
    if isinstance(data, dict) and isinstance(data.get("metrics"), dict):
        merged = dict(data)
        merged.update(data["metrics"])  # saved baseline file
        data = merged
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a JSON object")
    return data


def extract_perf(run: Dict[str, Any]) -> Dict[str, float]:
    """Pull the sentinel metrics out of a normalized run dict.  The
    bench headline value doubles as tokens_per_sec when the metric name
    says so."""
    out: Dict[str, float] = {}
    metric = str(run.get("metric", ""))
    if "tokens_per_sec" in metric and "value" in run:
        try:
            v = float(run["value"])
            if v > 0:
                out["tokens_per_sec"] = v
        except (TypeError, ValueError):
            pass
    for name in PERF_METRICS:
        if name in run:
            try:
                out[name] = float(run[name])
            except (TypeError, ValueError):
                continue
    return out


def environment_failure_reason(run: Dict[str, Any]) -> Optional[str]:
    """A *no-data* artifact's named reason, or ``None`` for a real run.

    Matches two shapes: an explicit ``environment_failure`` marker
    (``bench.py`` stamps it when its device probe fails), and the
    LEGACY r05-style probe-failure line — ``value`` 0 with an ``error``
    field and NO ``debug_bundle`` key.  The key matters: a bench that
    *crashed* (a code regression — OOM, assertion) also emits value 0 +
    error, but its line carries ``debug_bundle`` (``_emit_crash_line``)
    and no marker — that must stay a LOUD failure of the gate, never a
    skip.  ``perf check`` skips only genuine environment failures, with
    the reason printed."""
    if run.get("environment_failure"):
        return str(run.get("error") or "environment_failure marker set")
    err = run.get("error")
    if not err or "debug_bundle" in run:
        return None  # a crash artifact is a real failure, not a skip
    try:
        value = float(run.get("value", 0.0) or 0.0)
    except (TypeError, ValueError):
        value = 0.0
    if value == 0.0:
        return str(err)
    return None


def save_baseline(path: str, run: Dict[str, Any],
                  source: str = "") -> Dict[str, Any]:
    metrics = extract_perf(run)
    if not metrics:
        raise ValueError(
            "run carries none of the sentinel metrics "
            f"({', '.join(PERF_METRICS)}) — nothing to baseline")
    doc = {"created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "source": source, "metrics": metrics}
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2)
    os.replace(tmp, path)  # atomic: a concurrent check never sees a torn file
    return doc


def load_baseline(path: str) -> Dict[str, float]:
    with open(path) as fh:
        doc = json.load(fh)
    metrics = doc.get("metrics", doc)
    return {k: float(v) for k, v in metrics.items() if k in PERF_METRICS}


def check_regression(current: Dict[str, float], baseline: Dict[str, float],
                     tolerances: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """Compare run vs baseline metric-by-metric.

    Returns ``{regressions: [...], improvements: [...], compared: [...],
    skipped: [...]}`` — a metric present in only one side is *skipped*
    (named, never silently dropped), so adding a new bench field does
    not fail every old baseline."""
    tolerances = tolerances or {}
    out: Dict[str, Any] = {"regressions": [], "improvements": [],
                           "compared": [], "skipped": []}
    for name, (direction, default_tol) in PERF_METRICS.items():
        if name not in current or name not in baseline:
            if name in current or name in baseline:
                out["skipped"].append(name)
            continue
        cur, base = current[name], baseline[name]
        tol = float(tolerances.get(name, default_tol))
        floor = ABS_FLOORS.get(name, 0.0)
        entry = {"metric": name, "current": cur, "baseline": base,
                 "tolerance": tol, "direction": direction}
        out["compared"].append(name)
        if direction == "higher":
            limit = base * (1.0 - tol)
            entry["limit"] = limit
            if cur < limit:
                entry["delta_frac"] = (cur - base) / base if base else 0.0
                out["regressions"].append(entry)
            elif cur > base:
                out["improvements"].append(entry)
        else:
            limit = base * (1.0 + tol)
            entry["limit"] = limit
            if cur > limit and cur - base > floor:
                entry["delta_frac"] = (cur - base) / base if base else 0.0
                out["regressions"].append(entry)
            elif cur < base:
                out["improvements"].append(entry)
    return out


def format_check_report(result: Dict[str, Any]) -> str:
    lines: List[str] = []
    for r in result["regressions"]:
        arrow = "dropped" if r["direction"] == "higher" else "grew"
        lines.append(
            f"REGRESSION {r['metric']}: {r['baseline']:g} -> "
            f"{r['current']:g} ({arrow} {abs(r['delta_frac']):.1%}, "
            f"tolerance {r['tolerance']:.0%})")
    for r in result["improvements"]:
        lines.append(f"improved {r['metric']}: {r['baseline']:g} -> "
                     f"{r['current']:g}")
    ok = [m for m in result["compared"]
          if m not in {r["metric"] for r in result["regressions"]}
          and m not in {r["metric"] for r in result["improvements"]}]
    if ok:
        lines.append(f"within tolerance: {', '.join(ok)}")
    if result["skipped"]:
        lines.append("not comparable (present on one side only): "
                     + ", ".join(result["skipped"]))
    if not result["compared"]:
        lines.append("no overlapping metrics between run and baseline")
    return "\n".join(lines)


def parse_tolerances(specs: List[str]) -> Dict[str, float]:
    """``["mfu=0.05", "step_time_p50_ms=0.2"]`` → dict; unknown metric
    names are an error (a typo must not silently widen nothing)."""
    out: Dict[str, float] = {}
    for spec in specs or []:
        if "=" not in spec:
            raise ValueError(f"--tol {spec!r}: expected metric=fraction")
        name, _, frac = spec.partition("=")
        name = name.strip()
        if name not in PERF_METRICS:
            raise ValueError(f"--tol {name!r}: unknown metric "
                             f"(one of {', '.join(PERF_METRICS)})")
        out[name] = float(frac)
    return out
