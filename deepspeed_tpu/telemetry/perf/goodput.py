"""Goodput ledger — where the wall-clock actually goes.

MegaScale (arXiv:2402.15627) makes *goodput* — productive training time
over total time — the headline SLO for large runs, because every other
number (step time, MFU) silently excludes the time the run was NOT
stepping: compiles, stalls, rollbacks, restarts, checkpoint flushes.
PR 4 added rollbacks/restarts/skipped windows that consume real time no
metric accounted for; this ledger is that account.

Wall time is classified into buckets:

* ``productive`` — step execution time net of compile (the engine feeds
  ``step_time - compile_time`` per step);
* ``compile``    — lower+compile wall time (from the CompileTracker);
* ``stall``      — watchdog-detected no-progress intervals;
* ``recovery``   — resilience rollback/backoff time PLUS the lost work
  of the skipped data window (the policy reclassifies the failed
  window's step time from ``productive`` to ``recovery`` — those steps
  LOOKED productive until the rollback discarded them);
* ``checkpoint`` — blocking checkpoint/snapshot save time (the async
  engine only charges its blocking device→host capture).

``goodput() = productive / total``; a rolling fraction over the last
``window_s`` rides the watchdog ``heartbeat_payload`` so rank 0 can
publish cluster-wide goodput and the cluster manifest shows per-host
budgets.  Like every singleton in the telemetry stack it is cheap when
disabled (one attribute read) and explicit instances are testable.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, Optional

BUCKETS = ("productive", "compile", "stall", "recovery", "checkpoint",
           "profiler")


class GoodputLedger:
    """Bucketed wall-clock account with a rolling window."""

    def __init__(self, enabled: bool = False, window_s: float = 600.0,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = bool(enabled)
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        #: (ts, bucket, seconds) ring for the rolling fraction;
        #: reclassifications append a negative compensating entry
        self._window: "collections.deque" = collections.deque(maxlen=4096)

    def configure(self, enabled: Optional[bool] = None,
                  window_s: Optional[float] = None) -> "GoodputLedger":
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if window_s:
                self.window_s = float(window_s)
        return self

    def reset(self) -> None:
        with self._lock:
            self._totals = {b: 0.0 for b in BUCKETS}
            self._window.clear()

    # -- feeds -------------------------------------------------------------

    def add(self, bucket: str, seconds: float) -> None:
        if not self.enabled or seconds == 0.0:
            return
        if bucket not in self._totals:
            raise ValueError(f"unknown goodput bucket {bucket!r} "
                             f"(one of {BUCKETS})")
        s = float(seconds)
        with self._lock:
            self._totals[bucket] += s
            self._window.append((self._clock(), bucket, s))
        self._publish()

    def add_step(self, step_time_s: float, compile_s: float = 0.0) -> None:
        """Engine feed: one optimizer step's wall time, compile share
        split out (a compile-dominated first/rebucketed step must not
        read as productive throughput)."""
        compile_s = min(max(compile_s, 0.0), max(step_time_s, 0.0))
        self.add("compile", compile_s)
        self.add("productive", max(step_time_s - compile_s, 0.0))

    def reclassify(self, src: str, dst: str, seconds: float) -> None:
        """Move time between buckets after the fact — the rollback path:
        the skipped window's steps were charged ``productive`` as they
        ran, and the rollback proves that work was lost."""
        if not self.enabled or seconds <= 0.0:
            return
        with self._lock:
            moved = min(float(seconds), max(self._totals.get(src, 0.0), 0.0))
            self._totals[src] -= moved
            self._totals[dst] = self._totals.get(dst, 0.0) + moved
            now = self._clock()
            self._window.append((now, src, -moved))
            self._window.append((now, dst, moved))
        self._publish()

    # -- read side ---------------------------------------------------------

    def totals(self) -> Dict[str, float]:
        with self._lock:
            return {b: round(max(v, 0.0), 6)
                    for b, v in self._totals.items()}

    def total_seconds(self) -> float:
        with self._lock:
            return sum(max(v, 0.0) for v in self._totals.values())

    def goodput(self) -> float:
        """Cumulative productive fraction; 1.0 when nothing is recorded
        yet (an empty account is not a regression)."""
        with self._lock:
            total = sum(max(v, 0.0) for v in self._totals.values())
            if total <= 0.0:
                return 1.0
            return max(self._totals["productive"], 0.0) / total

    def rolling_goodput(self) -> float:
        """Productive fraction over the last ``window_s`` seconds — the
        number that rides heartbeats (a 3-day-old compile must not mask
        a stall happening NOW)."""
        cutoff = self._clock() - self.window_s
        sums: Dict[str, float] = {}
        with self._lock:
            for ts, bucket, s in self._window:
                if ts >= cutoff:
                    sums[bucket] = sums.get(bucket, 0.0) + s
        total = sum(max(v, 0.0) for v in sums.values())
        if total <= 0.0:
            return 1.0
        return max(sums.get("productive", 0.0), 0.0) / total

    def heartbeat_summary(self) -> Dict[str, float]:
        return {"goodput": round(self.rolling_goodput(), 4),
                "goodput_total": round(self.goodput(), 4)}

    def snapshot(self) -> Dict[str, Any]:
        """Bundle context provider payload (→ cluster manifest per-host
        budgets)."""
        return {"buckets_s": self.totals(),
                "goodput": round(self.goodput(), 4),
                "rolling_goodput": round(self.rolling_goodput(), 4),
                "window_s": self.window_s}

    def _publish(self) -> None:
        try:
            from .. import get_telemetry

            tel = get_telemetry()
            if not tel.enabled:
                return
            for b, v in self.totals().items():
                tel.set_gauge(f"goodput/{b}_seconds_total", v,
                              help=f"wall seconds classified {b}")
            tel.set_gauge("goodput/fraction", self.goodput(),
                          help="productive / total wall time")
        except Exception as e:  # metrics publish is best-effort
            from ...utils.logging import debug_once

            debug_once("goodput/publish",
                       f"goodput gauge publish failed ({e!r})")


_default = GoodputLedger()


def get_goodput_ledger() -> GoodputLedger:
    return _default


def configure_goodput_ledger(enabled: bool = True,
                             window_s: Optional[float] = None,
                             recorder: Any = None) -> GoodputLedger:
    """Resolve config into the global ledger; with a flight recorder the
    snapshot lands in every debug bundle (context ``goodput``), which is
    how the cluster manifest learns per-host budgets."""
    led = _default.configure(enabled=enabled, window_s=window_s)
    if recorder is not None and enabled:
        recorder.register_context("goodput", led.snapshot)
    return led
