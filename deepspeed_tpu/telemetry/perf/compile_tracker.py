"""Compile/recompile tracker — ``tracked_jit`` over every engine jit site.

The engine compiles ~19 distinct XLA programs with documented recompile
hazards (tail-batch shapes, 1-bit warmup boundaries, random-LTD keep
buckets — ``runtime/engine.py``), and until now not one compile event
was recorded anywhere: a recompile storm showed up only as mysteriously
slow steps.  This module is the missing ledger:

* :func:`tracked_jit` — a thin wrapper around ``jax.jit`` that goes
  through the AOT path (``jit(fn).lower(*args).compile()``) on the
  first call per **program signature** so lower and compile wall time
  are measured separately, and dispatches the cached executable on
  every later call (one dict lookup over a signature key — the same
  work jax's own C++ cache does).
* A **program signature**: the abstract avals (shape/dtype/weak-type)
  of every argument leaf, the donate set, and a ``static_context``
  dict for closure-baked statics (gas, 1-bit warmup flag, LTD keep
  bucket).  A second distinct signature at the same *site* is a
  **recompile**, and the event carries a structured diff naming the
  cause — which leaf, which dimension, old → new (shape / dtype /
  static / structure change).
* Counters/gauges in the metrics registry (``compile/events_total``,
  ``compile/recompiles_total``, ``compile/time_ms_total``,
  ``compile/live_programs``) and a per-site program table embedded in
  every flight-recorder debug bundle (context ``compile_programs``).

Anything the AOT path cannot handle (exotic arg types, backend quirks)
falls back to calling the plain jitted function — the event is still
recorded (with ``fallback: true`` and combined timing), the program
just isn't separately lower/compile-split.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...utils.logging import logger


def _leaf_sig(leaf: Any) -> Tuple:
    """(shape, dtype, weak_type) for array-likes; repr for the rest."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype),
                bool(getattr(leaf, "weak_type", False)))
    return ("pyval", repr(leaf))


def signature_of(args: Tuple, kwargs: Dict[str, Any],
                 static_context: Optional[Dict[str, Any]] = None,
                 donate: Tuple = ()) -> Dict[str, Any]:
    """The cross-call comparison key for one compiled program: per-leaf
    avals (keyed by argument path), the static context, the donate set."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves: Dict[str, Tuple] = {}
    for i, a in enumerate(args):
        for path, leaf in tree_flatten_with_path(a)[0]:
            leaves[f"arg{i}{keystr(path)}"] = _leaf_sig(leaf)
    for k in sorted(kwargs):
        for path, leaf in tree_flatten_with_path(kwargs[k])[0]:
            leaves[f"kwarg[{k}]{keystr(path)}"] = _leaf_sig(leaf)
    return {"leaves": leaves,
            "static": dict(static_context or {}),
            "donate": tuple(donate)}


def signature_key(sig: Dict[str, Any]) -> Tuple:
    """Hashable form of :func:`signature_of` (the program-cache key)."""
    return (tuple(sorted(sig["leaves"].items())),
            tuple(sorted((k, repr(v)) for k, v in sig["static"].items())),
            sig["donate"])


def diff_signatures(old: Dict[str, Any],
                    new: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Structured recompile-cause diff: which leaf / static key changed,
    and HOW (the changed dimension by index, dtype old→new, ...) — the
    line an operator reads to know *why* step N stalled for a compile."""
    causes: List[Dict[str, Any]] = []
    ol, nl = old["leaves"], new["leaves"]
    for name in sorted(set(ol) | set(nl)):
        a, b = ol.get(name), nl.get(name)
        if a == b:
            continue
        if a is None or b is None:
            causes.append({"kind": "structure_change", "leaf": name,
                           "old": a and list(a), "new": b and list(b)})
            continue
        if a[0] == "pyval" or b[0] == "pyval":
            causes.append({"kind": "value_change", "leaf": name,
                           "old": a[-1], "new": b[-1]})
            continue
        (ashape, adt, awk), (bshape, bdt, bwk) = a, b
        if ashape != bshape:
            if len(ashape) == len(bshape):
                for d, (x, y) in enumerate(zip(ashape, bshape)):
                    if x != y:
                        causes.append({"kind": "shape_change", "leaf": name,
                                       "dim": d, "old": x, "new": y})
            else:
                causes.append({"kind": "rank_change", "leaf": name,
                               "old": list(ashape), "new": list(bshape)})
        if adt != bdt:
            causes.append({"kind": "dtype_change", "leaf": name,
                           "old": adt, "new": bdt})
        if awk != bwk:
            causes.append({"kind": "weak_type_change", "leaf": name,
                           "old": awk, "new": bwk})
    for key in sorted(set(old["static"]) | set(new["static"])):
        a, b = old["static"].get(key), new["static"].get(key)
        if a != b:
            causes.append({"kind": "static_change", "key": key,
                           "old": a, "new": b})
    if old["donate"] != new["donate"]:
        causes.append({"kind": "donate_change",
                       "old": list(old["donate"]),
                       "new": list(new["donate"])})
    return causes


@dataclasses.dataclass
class CompileEvent:
    site: str
    kind: str                 # "compile" (first at site) | "recompile"
    program: int              # per-site program ordinal (0-based)
    lower_ms: float
    compile_ms: float
    total_ms: float
    n_leaves: int
    static: Dict[str, Any]
    causes: List[Dict[str, Any]]  # empty on the first compile of a site
    fallback: bool = False
    ts: float = dataclasses.field(default_factory=time.time)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class CompileTracker:
    """Per-site program table + compile-event stream.

    Cheap when disabled (``tracked_jit`` then returns plain ``jax.jit``
    output); when enabled every tracked site pays one signature build +
    dict lookup per call — noise next to an XLA dispatch.
    """

    def __init__(self, enabled: bool = False, max_events: int = 512):
        self.enabled = bool(enabled)
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        #: site -> list of program dicts (signature, timings, use counts)
        self._sites: Dict[str, List[Dict[str, Any]]] = {}
        self._events: List[CompileEvent] = []
        self.events_total = 0
        self.recompiles_total = 0
        self.time_ms_total = 0.0
        #: fns called with each CompileEvent (engine per-step attribution)
        self._listeners: List[Callable[[CompileEvent], Any]] = []
        #: fns called with (site, program, compiled_executable) right
        #: after a successful AOT compile — the anatomy plane's cost
        #: ledger harvests ``compiled.cost_analysis()`` here, at compile
        #: time, so the steady state pays nothing
        self._cost_harvesters: List[Callable[[str, int, Any], Any]] = []

    def configure(self, enabled: Optional[bool] = None,
                  max_events: Optional[int] = None) -> "CompileTracker":
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if max_events:
                self.max_events = int(max_events)
        return self

    def reset(self) -> None:
        with self._lock:
            self._sites = {}
            self._events = []
            self.events_total = 0
            self.recompiles_total = 0
            self.time_ms_total = 0.0
            self._listeners = []
            self._cost_harvesters = []

    def add_listener(self, fn: Callable[[CompileEvent], Any]) -> None:
        self._listeners.append(fn)

    def add_cost_harvester(self, fn: Callable[[str, int, Any], Any]
                           ) -> None:
        """Register ``fn(site, program, compiled)`` to run after each
        successful AOT compile (fallback-path programs have no
        executable and are not harvested)."""
        self._cost_harvesters.append(fn)

    def harvest_cost(self, site: str, program: int, compiled: Any) -> None:
        for fn in list(self._cost_harvesters):
            try:
                fn(site, program, compiled)
            except Exception as e:  # harvest is best-effort telemetry
                logger.warning(f"compile tracker cost harvest failed at "
                               f"{site} ({e!r})")

    # -- recording ---------------------------------------------------------

    def record(self, site: str, sig: Dict[str, Any], lower_ms: float,
               compile_ms: float, fallback: bool = False) -> CompileEvent:
        with self._lock:
            progs = self._sites.setdefault(site, [])
            causes: List[Dict[str, Any]] = []
            kind = "compile"
            if progs:
                kind = "recompile"
                causes = diff_signatures(progs[-1]["signature"], sig)
            ev = CompileEvent(
                site=site, kind=kind, program=len(progs),
                lower_ms=round(lower_ms, 3), compile_ms=round(compile_ms, 3),
                total_ms=round(lower_ms + compile_ms, 3),
                n_leaves=len(sig["leaves"]), static=dict(sig["static"]),
                causes=causes, fallback=fallback)
            progs.append({"signature": sig, "event": ev.to_dict(),
                          "calls": 0})
            self._events.append(ev)
            del self._events[:-self.max_events]
            self.events_total += 1
            if kind == "recompile":
                self.recompiles_total += 1
            self.time_ms_total += ev.total_ms
            live = sum(len(p) for p in self._sites.values())
            listeners = list(self._listeners)
        self._publish(ev, live)
        for fn in listeners:
            try:
                fn(ev)
            except Exception as e:
                logger.warning(f"compile tracker listener failed: {e!r}")
        if kind == "recompile":
            logger.info(
                f"compile tracker: RECOMPILE at {site} "
                f"(program #{ev.program}, {ev.total_ms:.0f}ms): "
                + ("; ".join(format_cause(c) for c in causes[:4])
                   or "no signature diff (first call after cache reset?)"))
        return ev

    def note_call(self, site: str, program: int) -> None:
        with self._lock:
            progs = self._sites.get(site)
            if progs and 0 <= program < len(progs):
                progs[program]["calls"] += 1

    def _publish(self, ev: CompileEvent, live_programs: int) -> None:
        try:
            from .. import get_telemetry

            tel = get_telemetry()
            tel.inc_counter("compile/events_total",
                            help="XLA compile events (tracked jit sites)")
            if ev.kind == "recompile":
                tel.inc_counter("compile/recompiles_total",
                                help="recompiles of an already-compiled "
                                     "site (shape/dtype/static change)")
            tel.inc_counter("compile/time_ms_total", v=ev.total_ms,
                            help="cumulative lower+compile wall time (ms)")
            tel.set_gauge("compile/live_programs", live_programs,
                          help="distinct compiled programs across sites")
            tel.emit_event("compile", ev.to_dict())
        except Exception as e:  # metrics publish is best-effort
            logger.debug(f"compile tracker: metrics publish failed ({e!r})")

    # -- read side ---------------------------------------------------------

    def events(self, last: Optional[int] = None) -> List[CompileEvent]:
        with self._lock:
            evs = list(self._events)
        return evs[-last:] if last else evs

    def table(self) -> Dict[str, Any]:
        """Per-site program table — the flight-recorder context provider
        (``context["compile_programs"]`` in every debug bundle)."""
        with self._lock:
            sites = {
                site: [{"program": p["event"]["program"],
                        "kind": p["event"]["kind"],
                        "lower_ms": p["event"]["lower_ms"],
                        "compile_ms": p["event"]["compile_ms"],
                        "total_ms": p["event"]["total_ms"],
                        "static": p["event"]["static"],
                        "causes": p["event"]["causes"],
                        "fallback": p["event"]["fallback"],
                        "calls": p["calls"]}
                       for p in progs]
                for site, progs in self._sites.items()}
            return {"events_total": self.events_total,
                    "recompiles_total": self.recompiles_total,
                    "time_ms_total": round(self.time_ms_total, 3),
                    "sites": sites}


def format_cause(c: Dict[str, Any]) -> str:
    """One-line human rendering of a recompile cause (shared with the
    CLI's bundle summary)."""
    k = c.get("kind")
    if k == "shape_change":
        return (f"{c['leaf']} dim {c['dim']}: {c['old']} -> {c['new']}")
    if k == "dtype_change":
        return f"{c['leaf']} dtype {c['old']} -> {c['new']}"
    if k == "static_change":
        return f"static {c['key']}: {c['old']} -> {c['new']}"
    return f"{k}: {c.get('leaf', c.get('key', ''))}"


class TrackedJit:
    """``jax.jit`` with a signature-keyed AOT cache + compile telemetry.

    Call surface matches the jitted function.  The ``lower`` attribute
    is forwarded so AOT callers keep working.
    """

    def __init__(self, fn: Callable, site: str, tracker: CompileTracker,
                 static_context: Optional[Dict[str, Any]] = None,
                 **jit_kwargs: Any):
        import jax

        self.site = site
        self.tracker = tracker
        self.static_context = dict(static_context or {})
        donate = jit_kwargs.get("donate_argnums", ())
        self._donate = (tuple(donate) if isinstance(donate, (tuple, list))
                        else (donate,))
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._programs: Dict[Tuple, Any] = {}  # sig key -> (idx, compiled)
        self._fell_back = False
        self._lock = threading.Lock()

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        if not self.tracker.enabled:
            return self._jitted(*args, **kwargs)
        sig = signature_of(args, kwargs, self.static_context, self._donate)
        key = signature_key(sig)
        with self._lock:
            entry = self._programs.get(key)
        if entry is not None:
            idx, compiled = entry
            self.tracker.note_call(self.site, idx)
            if compiled is None:  # this signature runs on the fallback path
                return self._jitted(*args, **kwargs)
            return compiled(*args, **kwargs)
        # cache miss: the AOT path, so lower and compile are timed apart
        compiled = None
        try:
            t0 = time.perf_counter()
            lowered = self._jitted.lower(*args, **kwargs)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
            lower_ms, compile_ms = (t1 - t0) * 1e3, (t2 - t1) * 1e3
            fallback = False
        except Exception as e:
            if not self._fell_back:
                self._fell_back = True
                logger.warning(
                    f"compile tracker: AOT lower/compile failed at "
                    f"{self.site} ({e!r}) — falling back to plain jit "
                    f"(combined timing)")
            t0 = time.perf_counter()
            out = self._jitted(*args, **kwargs)
            lower_ms, compile_ms = 0.0, (time.perf_counter() - t0) * 1e3
            fallback = True
        ev = self.tracker.record(self.site, sig, lower_ms, compile_ms,
                                 fallback=fallback)
        if compiled is not None:
            # compile-time cost harvest (anatomy plane): the AOT handle
            # is in hand exactly once, here — cost_analysis() now costs
            # the steady state nothing
            self.tracker.harvest_cost(self.site, ev.program, compiled)
        with self._lock:
            self._programs[key] = (ev.program, compiled)
        self.tracker.note_call(self.site, ev.program)
        if fallback:
            return out
        try:
            return compiled(*args, **kwargs)
        except Exception as e:
            # an executable the AOT path built but cannot dispatch (layout
            # or weak-type mismatch): route THIS signature through the
            # plain jitted path from now on
            logger.warning(f"compile tracker: compiled dispatch failed at "
                           f"{self.site} ({e!r}) — using plain jit for "
                           f"this signature")
            with self._lock:
                self._programs[key] = (ev.program, None)
            return self._jitted(*args, **kwargs)


def tracked_jit(fn: Callable, site: str,
                tracker: Optional[CompileTracker] = None,
                static_context: Optional[Dict[str, Any]] = None,
                **jit_kwargs: Any):
    """``jax.jit`` that records compile/recompile events at ``site``.

    With ``tracker=None`` (tracking off) this IS ``jax.jit(fn, **kw)`` —
    zero overhead, zero behavior change."""
    import jax

    if tracker is None:
        return jax.jit(fn, **jit_kwargs)
    return TrackedJit(fn, site, tracker, static_context=static_context,
                      **jit_kwargs)


_default = CompileTracker()


def get_compile_tracker() -> CompileTracker:
    return _default


def configure_compile_tracker(enabled: bool = True,
                              max_events: Optional[int] = None,
                              recorder: Any = None) -> CompileTracker:
    """Resolve config into the global tracker; when a flight recorder is
    given, register the per-site program table as a bundle context
    provider so every debug bundle answers "what compiled, when, why"."""
    trk = _default.configure(enabled=enabled, max_events=max_events)
    if recorder is not None and enabled:
        recorder.register_context("compile_programs", trk.table)
    return trk
