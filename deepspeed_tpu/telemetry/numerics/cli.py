"""``python -m deepspeed_tpu.telemetry numerics {show,top,diff}``.

The read side of the numerics plane:

* ``numerics show <bundle>`` — the forensic verdict (first non-finite
  layer), worst-case summary scalars, grad-path norms, and MoE gate
  stats of one bundle (``numerics.json`` when present — a NaN-forensics
  bundle — else the manifest's ``context.numerics`` section).
* ``numerics top <bundle>`` — probes ranked by a chosen stat field
  (default ``subnormal_frac``): where underflow/overflow concentrates.
* ``numerics diff <a> <b>`` — two captures over time: per-probe deltas
  on underflow/saturation/rms plus an UNDERFLOW CREEP verdict — exit 3
  when the worst subnormal fraction grew beyond threshold (scriptable,
  same contract as ``mem diff``/``perf check``).

Every command works on plain directories — no store, no device.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Optional

from .forensics import NUMERICS_JSON
from .stats import STAT_FIELDS

#: diff verdict: worst subnormal_frac growing past this is creep
CREEP_GROW_ABS = 0.05


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


def load_numerics_section(bundle: str) -> Optional[Dict[str, Any]]:
    """Best numerics payload in a bundle dir: ``numerics.json`` (NaN
    forensics) wins; else the manifest's ``context.numerics``."""
    nj = os.path.join(bundle, NUMERICS_JSON)
    if os.path.exists(nj):
        try:
            with open(nj) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            pass
    manifest = os.path.join(bundle, "bundle.json")
    if not os.path.exists(manifest):
        return None
    try:
        with open(manifest) as fh:
            ctx = (json.load(fh).get("context") or {})
    except (OSError, ValueError):
        return None
    num = ctx.get("numerics")
    return num if isinstance(num, dict) else None


def _resolve(path: str) -> Optional[str]:
    from ..cli import _resolve_bundle

    return _resolve_bundle(path)


# ---------------------------------------------------------------------------
# show
# ---------------------------------------------------------------------------

def cmd_numerics_show(args: argparse.Namespace) -> int:
    bundle = _resolve(args.bundle)
    if bundle is None:
        return _fail(f"{args.bundle}: not a debug bundle")
    num = load_numerics_section(bundle)
    if num is None:
        return _fail(f"{bundle}: no numerics section (numerics.json or "
                     f"manifest context.numerics)")
    print(f"bundle: {bundle}")
    if num.get("step") is not None:
        print(f"  step: {num['step']}  loss: {num.get('loss', '?')}")
    first = num.get("first_nonfinite")
    if first:
        st = (num.get("probes") or {}).get(first, {})
        print(f"  FIRST NON-FINITE: {first} "
              f"(nonfinite={st.get('nonfinite', 0):.0f}, "
              f"absmax={st.get('absmax', 0):.3g})")
    elif "first_nonfinite" in num:
        print("  no non-finite probe — poison not in forward activations")
    summary = num.get("summary") or {}
    if summary:
        print("  summary: " + "  ".join(
            f"{k}={v:.4g}" for k, v in sorted(summary.items())))
    grads = num.get("grads") or {}
    scalars = {k: v for k, v in grads.items() if not isinstance(v, list)}
    if scalars:
        print("  grad norms: " + "  ".join(
            f"{k}={v:.4g}" for k, v in sorted(scalars.items())))
    ratios = num.get("update_ratio") or {}
    if ratios:
        print("  update/param ratios: " + "  ".join(
            f"{k}={v:.3g}" for k, v in sorted(ratios.items())
            if not isinstance(v, list)))
    moe = num.get("moe") or {}
    if moe:
        def _fmt(v):
            if isinstance(v, list):
                flat = v if not (v and isinstance(v[0], list)) \
                    else [x for row in v for x in row]
                if not flat:
                    return "[]"
                return (f"mean={sum(flat)/len(flat):.3g} "
                        f"min={min(flat):.3g} max={max(flat):.3g}")
            return f"{v:.4g}"
        print("  moe gate: " + "  ".join(
            f"{k}({_fmt(v)})" for k, v in sorted(moe.items())))
    probes = num.get("probes") or {}
    if probes and args.all:
        print(f"  probes ({len(probes)}):")
        for name in num.get("order") or sorted(probes):
            st = probes.get(name) or {}
            print(f"    {name:<28} absmax={st.get('absmax', 0):>10.3g} "
                  f"rms={st.get('rms', 0):>10.3g} "
                  f"sub={st.get('subnormal_frac', 0):>7.2%} "
                  f"sat={st.get('saturated_frac', 0):>7.2%} "
                  f"nonfinite={st.get('nonfinite', 0):.0f}")
    elif probes:
        print(f"  probes: {len(probes)} captured (use --all to list)")
    return 0


# ---------------------------------------------------------------------------
# top
# ---------------------------------------------------------------------------

def cmd_numerics_top(args: argparse.Namespace) -> int:
    bundle = _resolve(args.bundle)
    if bundle is None:
        return _fail(f"{args.bundle}: not a debug bundle")
    num = load_numerics_section(bundle)
    probes = (num or {}).get("probes") or {}
    if not probes:
        return _fail(f"{bundle}: no per-probe capture in the numerics "
                     f"section")
    field = args.field
    ranked = sorted(probes.items(),
                    key=lambda kv: -float(kv[1].get(field, 0.0)))
    print(f"bundle: {bundle}")
    print(f"  top {min(args.k, len(ranked))} probes by {field}:")
    for name, st in ranked[:args.k]:
        print(f"    {float(st.get(field, 0.0)):>10.4g}  {name}  "
              f"(absmax={st.get('absmax', 0):.3g} "
              f"rms={st.get('rms', 0):.3g})")
    return 0


# ---------------------------------------------------------------------------
# diff — the underflow-creep verdict
# ---------------------------------------------------------------------------

def diff_numerics(a: Dict[str, Any], b: Dict[str, Any],
                  creep_abs: float = CREEP_GROW_ABS) -> Dict[str, Any]:
    """Compare OLD ``a`` against NEW ``b``: per-probe subnormal /
    saturation / rms deltas + a creep verdict when the worst subnormal
    fraction grew by more than ``creep_abs`` (absolute)."""
    pa, pb = a.get("probes") or {}, b.get("probes") or {}
    findings = []
    deltas: Dict[str, Dict[str, float]] = {}
    for name in sorted(set(pa) & set(pb)):
        sa, sb = pa[name], pb[name]
        d = {f: float(sb.get(f, 0.0)) - float(sa.get(f, 0.0))
             for f in ("subnormal_frac", "saturated_frac", "rms",
                       "nonfinite")}
        if any(d.values()):
            deltas[name] = d
        if d["subnormal_frac"] > creep_abs:
            findings.append(
                f"probe '{name}' subnormal_frac grew "
                f"{sa.get('subnormal_frac', 0):.2%} -> "
                f"{sb.get('subnormal_frac', 0):.2%}")
        if d["nonfinite"] > 0:
            findings.append(f"probe '{name}' went non-finite "
                            f"({d['nonfinite']:.0f} new bad elements)")
    wa = max((float(s.get("subnormal_frac", 0.0)) for s in pa.values()),
             default=0.0)
    wb = max((float(s.get("subnormal_frac", 0.0)) for s in pb.values()),
             default=0.0)
    creep = wb - wa > creep_abs or any("non-finite" in f for f in findings)
    return {"creep": bool(creep or findings), "findings": findings,
            "deltas": deltas, "worst_subnormal": (wa, wb)}


def cmd_numerics_diff(args: argparse.Namespace) -> int:
    a, b = _resolve(args.a), _resolve(args.b)
    if a is None or b is None:
        return _fail("numerics diff needs two debug bundle directories")
    na, nb = load_numerics_section(a), load_numerics_section(b)
    if na is None or nb is None:
        missing = a if na is None else b
        return _fail(f"{missing}: no numerics section")
    result = diff_numerics(na, nb, creep_abs=args.creep_abs)
    print(f"A (old): {a}\nB (new): {b}")
    wa, wb = result["worst_subnormal"]
    print(f"worst subnormal_frac: {wa:.2%} -> {wb:.2%}")
    shown = 0
    for name, d in sorted(result["deltas"].items(),
                          key=lambda kv: -abs(kv[1]["subnormal_frac"])):
        if shown >= args.k:
            break
        print(f"  {name:<28} dsub={d['subnormal_frac']:+.2%} "
              f"dsat={d['saturated_frac']:+.2%} drms={d['rms']:+.3g}")
        shown += 1
    if result["creep"]:
        print("CREEP VERDICT: " + ("; ".join(result["findings"])
                                   or f"worst subnormal_frac grew "
                                      f"{wb - wa:+.2%}"))
        return 3
    print(f"no underflow creep (growth within {args.creep_abs:.0%} abs)")
    return 0


# ---------------------------------------------------------------------------
# parser wiring (called from telemetry/cli.py build_parser)
# ---------------------------------------------------------------------------

def add_numerics_parser(sub: Any) -> None:
    n = sub.add_parser("numerics",
                       help="tensor-health forensics: show/top/diff "
                            "bundle numerics sections (diff exits 3 on "
                            "an underflow-creep verdict)")
    nsub = n.add_subparsers(dest="numerics_cmd", required=True)
    ns = nsub.add_parser("show", help="one bundle's numerics verdict "
                                      "and summary")
    ns.add_argument("bundle")
    ns.add_argument("--all", action="store_true",
                    help="list every captured probe")
    ns.set_defaults(fn=cmd_numerics_show)
    nt = nsub.add_parser("top", help="probes ranked by a stat field")
    nt.add_argument("bundle")
    nt.add_argument("-k", type=int, default=10)
    nt.add_argument("--field", default="subnormal_frac",
                    choices=list(STAT_FIELDS))
    nt.set_defaults(fn=cmd_numerics_top)
    nd = nsub.add_parser("diff", help="diff two captures; exit 3 on "
                                      "underflow-creep verdict")
    nd.add_argument("a", help="older bundle")
    nd.add_argument("b", help="newer bundle")
    nd.add_argument("-k", type=int, default=10,
                    help="max per-probe delta rows to print")
    nd.add_argument("--creep-abs", type=float, default=CREEP_GROW_ABS,
                    help="absolute subnormal_frac growth that "
                         f"constitutes creep (default {CREEP_GROW_ABS})")
    nd.set_defaults(fn=cmd_numerics_diff)
