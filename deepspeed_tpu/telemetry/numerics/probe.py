"""In-graph probe tags + the trace-time collector.

The contract that makes the plane free when off: :func:`probe` is an
IDENTITY — ``probe("resid", x)`` returns ``x`` itself (the same Python
object, not a copy) unless a collector is active *at trace time*.  The
enable decision is host-side module state read while JAX traces, never
a traced value — so a model instrumented with probes compiles to the
bitwise-same jaxpr as the uninstrumented model when the plane is off,
and turning the plane ON builds a SEPARATE program at its own jit site
(``engine/train_step_numerics``) instead of recompiling the base step.

Collection rides the step's output pytree: every probe folds its tensor
into an 8-scalar stat vector (:func:`~.stats.tensor_stats`) registered
on the active :class:`Collector`; the engine harvests the collector
into a tiny ``{name: array}`` dict returned next to the metrics — zero
host callbacks, one device→host transfer of a few hundred floats on
sampled steps only.

Two transform boundaries need an explicit bracket, because a probe's
stat tracer must EXIT the scope it was created in:

* ``lax.scan`` over stacked layers (the decoder trunk) and over
  gradient-accumulation microbatches: the body wraps itself in
  :func:`scan_mark` / :func:`scan_drain` — drain pops the body's own
  entries into an index-keyed dict returned as the body's scan ``ys``
  (names ride the dict KEYS, which are static pytree structure, so
  ``lax.scan`` stacks the values to ``[n, ...]`` and the names survive
  for free) — and :func:`scan_collect` re-registers the stacked result
  after the scan closes.  Draining inside the body keeps re-traces
  (``jax.checkpoint``, linearize) balanced: each trace pops exactly
  what it pushed.  When no collector is active every bracket call
  returns ``None`` and the body's ``ys`` stays ``None`` — today's
  jaxpr.
* ``value_and_grad``: the engine's loss closure drains the forward's
  entries and returns them via ``has_aux`` (see ``_grad_core``).

Regions that can NEVER carry a probe out (``shard_map`` bodies,
``lax.cond`` branches) suppress collection with :func:`suppressed` —
probes inside become identities for that region only.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .stats import STAT_FIELDS, stats_to_dict, tensor_stats

#: entry-name prefixes that are NOT probe stat vectors
MOE_PREFIX = "moe/"
GRAD_PREFIX = "grad/"
UPDATE_PREFIX = "update_ratio/"
#: key order prefix width: "0007:" — keeps sorted(dict) == program
#: order through jit/scan pytree round-trips (which sort dict keys)
_SEQ_W = 4


def _key(i: int, name: str) -> str:
    return f"{i:0{_SEQ_W}d}:{name}"


def _split_key(key: str) -> Tuple[int, str]:
    head, sep, rest = key.partition(":")
    if sep and head.isdigit():
        return int(head), rest
    return 1 << 30, key


class Collector:
    """One sampled (or forensic) capture: trace-time registry of
    ``(name, tracer)`` entries in program order."""

    def __init__(self, probes: bool = True, moe: bool = True,
                 tag: str = "sample"):
        self.want_probes = bool(probes)
        self.want_moe = bool(moe)
        self.tag = tag
        self.entries: List[Tuple[str, Any]] = []
        self._seq = 0  # monotonic across harvests — order survives resets

    def add(self, name: str, value: Any) -> None:
        self.entries.append((name, value))

    def harvest(self, reset: bool = True) -> Dict[str, Any]:
        """Entries → index-keyed ``{"0003:name": array}`` dict.  The
        index prefix makes SORTED key order equal program order — jit
        and scan rebuild dict pytrees key-sorted, so insertion order
        alone would not survive the round trip."""
        out: Dict[str, Any] = {}
        for name, value in self.entries:
            out[_key(self._seq, name)] = value
            self._seq += 1
        if reset:
            self.entries = []
        return out


# active collector is process-global but guarded: the engine activates
# it only around the traced call, and tests scrub it via reset()
_lock = threading.Lock()
_active: Optional[Collector] = None


class collecting:
    """``with collecting(coll): step_fn(...)`` — activates ``coll`` for
    the duration of the trace happening inside the block."""

    def __init__(self, collector: Optional[Collector]):
        self.collector = collector
        self._prev: Optional[Collector] = None

    def __enter__(self) -> Optional[Collector]:
        global _active
        with _lock:
            self._prev = _active
            _active = self.collector
        return self.collector

    def __exit__(self, *exc) -> None:
        global _active
        with _lock:
            _active = self._prev


class suppressed(collecting):
    """``with suppressed(): ...`` — probes become identities inside the
    block.  Used around regions whose tracers cannot legally escape
    (``shard_map`` bodies, ``lax.cond`` branches such as random-LTD's
    per-layer routing)."""

    def __init__(self) -> None:
        super().__init__(None)


def active() -> Optional[Collector]:
    return _active


def reset() -> None:
    """Test isolation: drop any active collector."""
    global _active
    with _lock:
        _active = None


# -- the tags models call ---------------------------------------------------

def probe(name: str, x: Any) -> Any:
    """Tag ``x`` for tensor-health stats.  Identity (returns ``x``
    itself) unless a probing collector is active at trace time."""
    c = _active
    if c is None or not c.want_probes:
        return x
    c.add(name, tensor_stats(x))
    return x


def moe_stats(meta: Dict[str, Any]) -> None:
    """Record gate statistics from a ``top_k_gating`` meta dict.  No-op
    without an active moe-accepting collector — callers never branch."""
    c = _active
    if c is None or not c.want_moe:
        return
    for key in ("load", "entropy", "drop_rate", "overflow_frac"):
        if key in meta:
            c.add(MOE_PREFIX + key, meta[key])


# -- scan bracket (stacked-layer models, gas microbatch scans) --------------

def scan_mark() -> Optional[int]:
    """Top of a scanned body (or a ``value_and_grad`` loss closure):
    remember how many entries exist so the matching :func:`scan_drain`
    pops only this region's additions."""
    c = _active
    if c is None:
        return None
    return len(c.entries)


def scan_drain(mark: Optional[int]) -> Optional[Dict[str, Any]]:
    """Bottom of the region: pop the entries added since ``mark`` and
    return them as an index-keyed dict — the body's scan ``ys`` (or the
    loss closure's ``has_aux`` aux).  Names ride the dict keys, so the
    structure is self-describing through any pytree transform."""
    c = _active
    if c is None or mark is None:
        return None
    popped = c.entries[mark:]
    del c.entries[mark:]
    if not popped:
        return None
    return {_key(i, name): v for i, (name, v) in enumerate(popped)}


def combine_stats(stacked: Any, name: str):
    """Fold the leading axis of a stacked stat array with field-aware
    reductions (gas-microbatch folding): counts sum, extrema take
    min/max, fractions and rms combine size-weighted.  Non-probe
    entries (moe/grad) just take the mean."""
    import jax.numpy as jnp

    is_vec = (getattr(stacked, "ndim", 0) >= 1
              and stacked.shape[-1] == len(STAT_FIELDS)
              and not name.startswith((MOE_PREFIX, GRAD_PREFIX,
                                       UPDATE_PREFIX)))
    if not is_vec:
        return jnp.mean(stacked, axis=0)
    f = {fld: i for i, fld in enumerate(STAT_FIELDS)}
    size = stacked[..., f["size"]]
    tot = jnp.maximum(jnp.sum(size, axis=0), 1.0)

    def wmean(idx):
        return jnp.sum(stacked[..., idx] * size, axis=0) / tot

    mn = stacked[..., f["min_nonzero"]]
    mn = jnp.min(jnp.where(mn > 0.0, mn, jnp.inf), axis=0)
    return jnp.stack([
        jnp.sum(stacked[..., f["nonfinite"]], axis=0),
        jnp.max(stacked[..., f["absmax"]], axis=0),
        jnp.where(jnp.isfinite(mn), mn, 0.0),
        jnp.sqrt(jnp.sum(jnp.square(stacked[..., f["rms"]]) * size, axis=0)
                 / tot),
        wmean(f["zero_frac"]),
        wmean(f["subnormal_frac"]),
        wmean(f["saturated_frac"]),
        jnp.sum(size, axis=0),
    ], axis=-1)


def scan_collect(ys: Optional[Dict[str, Any]],
                 combine: bool = False) -> None:
    """After the scan closes: re-register the stacked per-iteration
    values (each leaf now ``[n, ...]``).  ``combine=True`` folds the
    stacked axis with :func:`combine_stats` (the gas-microbatch fold);
    ``combine=False`` keeps it (the per-layer axis the forensics
    bisect on)."""
    c = _active
    if c is None or not ys:
        return
    for key in sorted(ys, key=_split_key):
        _, name = _split_key(key)
        value = ys[key]
        c.add(name, combine_stats(value, name) if combine else value)


# -- grad-path helpers (engine step_fn) -------------------------------------

def grad_stats(grads: Any, updates: Any, params: Any) -> Dict[str, Any]:
    """Per-top-level-module grad norms + update/param ratios, sliced
    from the step's existing pytrees (no extra forward).  A stacked
    ``layers`` module additionally yields a per-layer ``[L]`` grad-norm
    vector — the series ``layer_grad_explosion`` bisects on."""
    import jax
    import jax.numpy as jnp

    def _sq(tree, axes_from: int = 0):
        leaves = jax.tree_util.tree_leaves(tree)
        tot = jnp.float32(0.0)
        for lf in leaves:
            lf32 = lf.astype(jnp.float32)
            if axes_from:
                tot = tot + jnp.sum(jnp.square(lf32),
                                    axis=tuple(range(axes_from, lf32.ndim)))
            else:
                tot = tot + jnp.sum(jnp.square(lf32))
        return tot

    out: Dict[str, Any] = {}
    if isinstance(grads, dict):
        for key, sub in grads.items():
            out[GRAD_PREFIX + key] = jnp.sqrt(_sq(sub))
            if key == "layers":
                # leaves are [L, ...]: reduce every axis but the first
                out[GRAD_PREFIX + "per_layer"] = jnp.sqrt(_sq(sub, 1))
        if isinstance(updates, dict) and isinstance(params, dict):
            for key in grads:
                if key in updates and key in params:
                    un = jnp.sqrt(_sq(updates[key]))
                    pn = jnp.sqrt(_sq(params[key]))
                    out[UPDATE_PREFIX + key] = un / jnp.maximum(pn, 1e-12)
    else:
        out[GRAD_PREFIX + "all"] = jnp.sqrt(_sq(grads))
    return out


# -- host-side decode -------------------------------------------------------

def decode(named: Dict[str, Any]) -> Dict[str, Any]:
    """The harvested ``{"0003:name": device array}`` dict → a
    JSON-ready summary::

        {"probes": {flat_name: {field: float}},   # program order
         "order":  [flat_name, ...],
         "grads":  {module: float, "per_layer": [...]},
         "update_ratio": {module: float},
         "moe":    {stat: float or [..] list}}

    Probe entries with a leading layer axis (``[L, 8]``, the scanned
    decoder trunk) expand layer-major — ``layer00/attn_out``,
    ``layer00/mlp_out``, ``layer01/...`` — so "first nonfinite in
    program order" is a plain list walk.
    """
    probes: Dict[str, Dict[str, float]] = {}
    order: List[str] = []
    grads: Dict[str, Any] = {}
    ratios: Dict[str, Any] = {}
    moe: Dict[str, Any] = {}

    def _scalarize(v):
        a = np.asarray(v, dtype=np.float64)
        return float(a) if a.ndim == 0 else a.tolist()

    items = sorted(named.items(), key=lambda kv: _split_key(kv[0]))
    nfields = len(STAT_FIELDS)
    stacked = [(name, np.asarray(v)) for k, v in items
               for name in [_split_key(k)[1]]
               if not name.startswith((MOE_PREFIX, GRAD_PREFIX,
                                       UPDATE_PREFIX))
               and np.asarray(v).ndim == 2
               and np.asarray(v).shape[-1] == nfields]
    stacked_done = False
    for key, value in items:
        name = _split_key(key)[1]
        if name.startswith(MOE_PREFIX):
            moe[name[len(MOE_PREFIX):]] = _scalarize(value)
        elif name.startswith(GRAD_PREFIX):
            grads[name[len(GRAD_PREFIX):]] = _scalarize(value)
        elif name.startswith(UPDATE_PREFIX):
            ratios[name[len(UPDATE_PREFIX):]] = _scalarize(value)
        else:
            arr = np.asarray(value)
            if arr.shape == (nfields,):
                probes[name] = stats_to_dict(arr)
                order.append(name)
            elif arr.ndim == 2 and arr.shape[-1] == nfields:
                # the scanned-layer block: expand ONCE, layer-major, at
                # the position of its first member
                if stacked_done:
                    continue
                num_layers = max(a.shape[0] for _, a in stacked)
                for li in range(num_layers):
                    for n, a in stacked:
                        if li < a.shape[0]:
                            flat = f"layer{li:02d}/{n}"
                            probes[flat] = stats_to_dict(a[li])
                            order.append(flat)
                stacked_done = True
            else:  # unknown shape: keep raw rather than drop
                moe[name] = _scalarize(value)
    return {"probes": probes, "order": order, "grads": grads,
            "update_ratio": ratios, "moe": moe}


def summarize(decoded: Dict[str, Any]) -> Dict[str, float]:
    """Worst-case scalars for gauges/health from a decoded capture."""
    probes = decoded.get("probes", {})
    out = {
        "nonfinite_total": sum(p.get("nonfinite", 0.0)
                               for p in probes.values()),
        "absmax": max((p.get("absmax", 0.0) for p in probes.values()),
                      default=0.0),
        "underflow_frac": max((p.get("subnormal_frac", 0.0)
                               for p in probes.values()), default=0.0),
        "saturated_frac": max((p.get("saturated_frac", 0.0)
                               for p in probes.values()), default=0.0),
        "zero_frac": max((p.get("zero_frac", 0.0)
                          for p in probes.values()), default=0.0),
        "probe_count": float(len(probes)),
    }
    per_layer = decoded.get("grads", {}).get("per_layer")
    if isinstance(per_layer, list) and per_layer:
        finite = [g for g in per_layer if np.isfinite(g)]
        out["layer_grad_max"] = float(max(per_layer))
        out["layer_grad_median"] = float(np.median(finite)) if finite else 0.0
        out["layer_grad_argmax"] = float(int(np.argmax(per_layer)))
    moe = decoded.get("moe", {})

    def _mean(v):
        arr = np.asarray(v, dtype=np.float64)
        return float(arr.mean()) if arr.size else 0.0

    if "entropy" in moe:
        out["gate_entropy"] = _mean(moe["entropy"])
        load_arr = np.asarray(moe.get("load", []), dtype=np.float64)
        n_expert = load_arr.shape[-1] if load_arr.ndim else 0
        if n_expert > 1:
            # fraction of uniform (ln E): 1.0 = perfectly balanced
            # router, → 0 = collapse; E-independent, so the
            # router_collapse floor means the same thing at E=4 and E=64
            out["gate_entropy_frac"] = float(
                out["gate_entropy"] / np.log(n_expert))
    if "drop_rate" in moe:
        out["moe_drop_rate"] = _mean(moe["drop_rate"])
    if "overflow_frac" in moe:
        out["moe_overflow_frac"] = _mean(moe["overflow_frac"])
    if "load" in moe:
        # load is expert-load fractions [E] (or [L, E]): the max/mean
        # imbalance ratio is the one-number hot-expert signal
        arr = np.asarray(moe["load"], dtype=np.float64)
        if arr.size:
            flat = arr.reshape(-1, arr.shape[-1]) if arr.ndim > 1 \
                else arr[None]
            means = flat.mean(axis=1)
            ratio = np.where(means > 0, flat.max(axis=1) / np.maximum(
                means, 1e-12), 0.0)
            out["moe_load_imbalance"] = float(ratio.max())
    return out
