"""NaN origin bisection — the forensic capture after a non-finite loss.

``nan_loss`` rollback (resilience, PR 4) could always say *that* the
run diverged; this module makes the failure NAME the first bad layer.
When the engine sees a fenced non-finite loss with the numerics plane
enabled, it re-runs the loss forward on the SAME failed ``(state,
batch)`` with every probe on (its own jit site,
``engine/numerics_forensics`` — compiled once, only ever on failure),
decodes the capture, and walks the probes in program order: the first
one with ``nonfinite > 0`` is where the poison entered.

The artifact trail mirrors the memory plane's OOM forensics
(:mod:`..memory.oom`): a :class:`NonFiniteOriginReport` exception-style
report object, a ``numerics.json`` side file in the debug bundle, and a
flight-recorder annotation — so ``telemetry numerics show <bundle>``
and the rollback annotation both read the same record.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from ...utils.logging import logger
from .probe import decode, summarize
from .stats import first_nonfinite

#: side-file name inside a debug bundle (next to memory.json/bundle.json)
NUMERICS_JSON = "numerics.json"


class NonFiniteOriginReport(RuntimeError):
    """A non-finite loss, localized: carries the first bad probe (layer)
    in program order plus the full forensic capture.  Raisable like
    :class:`~..memory.oom.HBMExhaustedError` but normally just attached
    to the health event / rollback annotation."""

    def __init__(self, message: str, first_layer: str = "",
                 first_probe: str = "", step: int = -1,
                 bundle_path: Optional[str] = None,
                 report: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.first_layer = first_layer
        self.first_probe = first_probe
        self.step = step
        self.bundle_path = bundle_path
        self.report = report or {}
        #: same contract as HBMExhaustedError: a bundle already written
        #: for this failure suppresses the excepthook's duplicate dump
        self.ds_bundle_path = bundle_path


def build_report(named: Dict[str, Any], step: int,
                 loss: float = float("nan")) -> Dict[str, Any]:
    """Harvested forensic capture → the ``numerics.json`` document."""
    decoded = decode(named)
    first = first_nonfinite(decoded["probes"], decoded["order"])
    # "layer07/attn_out" → layer "layer07", probe "attn_out"; unscanned
    # probe names ("embed", "logits") are their own layer
    layer, _, site = first.partition("/")
    report = {
        "step": int(step),
        "loss": float(loss) if loss == loss else "nan",
        "first_nonfinite": first,
        "first_layer": layer,
        "first_probe": site or layer,
        "summary": summarize(decoded),
        "probes": decoded["probes"],
        "order": decoded["order"],
        "grads": decoded["grads"],
        "update_ratio": decoded["update_ratio"],
        "moe": decoded["moe"],
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    return report


def write_numerics_json(bundle_dir: str,
                        report: Dict[str, Any]) -> Optional[str]:
    """Drop ``numerics.json`` next to a bundle's ``bundle.json``
    (atomic tmp+replace, best-effort — forensics must never add a
    second failure to the first)."""
    try:
        os.makedirs(bundle_dir, exist_ok=True)
        path = os.path.join(bundle_dir, NUMERICS_JSON)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(report, fh, indent=2, default=str)
        os.replace(tmp, path)
        return path
    except OSError as e:
        logger.error(f"numerics: failed to write {NUMERICS_JSON}: {e!r}")
        return None


def report_from_capture(named: Dict[str, Any], step: int, loss: float,
                        recorder: Any = None) -> NonFiniteOriginReport:
    """Decode a forensic capture, annotate the flight recorder, dump a
    bundle when a recorder is armed, and return the report object."""
    doc = build_report(named, step, loss)
    first = doc["first_nonfinite"]
    msg = (f"non-finite loss at step {step}: first bad tensor is "
           f"'{first}' (nonfinite="
           f"{doc['probes'].get(first, {}).get('nonfinite', 0):.0f})"
           if first else
           f"non-finite loss at step {step}: forward re-run came back "
           f"finite — the poison is in the grad/optimizer path or the "
           f"batch, not the forward activations")
    bundle_path = None
    if recorder is not None:
        try:
            recorder.annotate("numerics_nonfinite", {
                "step": step, "first_nonfinite": first,
                "first_layer": doc["first_layer"],
                "summary": doc["summary"]})
            bundle_path = recorder.dump(reason="nan_loss_forensics")
            if bundle_path:
                write_numerics_json(bundle_path, doc)
        except Exception as e:  # diagnostics must not mask the rollback
            logger.error(f"numerics: forensic bundle dump failed: {e!r}")
    return NonFiniteOriginReport(
        msg, first_layer=doc["first_layer"], first_probe=doc["first_probe"],
        step=step, bundle_path=bundle_path, report=doc)
