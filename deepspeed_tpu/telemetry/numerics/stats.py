"""The per-tensor stat vector — the numerics plane's unit of record.

Every probe folds a tensor into EIGHT fp32 scalars (``STAT_FIELDS``),
computed in-graph so the jitted step never round-trips to the host:

``nonfinite``   count of NaN/Inf elements (the forensic localizer keys
                on this: first layer in program order with nonfinite>0)
``absmax``      max |x| over the finite elements (overflow watch)
``min_nonzero`` smallest nonzero |x| among finite elements (how close
                the tensor's tail sits to the representable floor)
``rms``         root-mean-square of the finite elements (scale drift)
``zero_frac``   exact-zero fraction (dead units / hard underflow)
``subnormal_frac``  fraction of NONZERO finite elements with
                |x| < finfo(dtype).tiny * 2**UNDERFLOW_MARGIN_BITS —
                already-subnormal values plus values within a few
                exponent steps of the dtype's flush floor.  The margin
                matters because XLA (CPU and TPU) flushes true
                subnormals to zero — by the time a probe sees the
                tensor those are ``zero_frac``; the recoverable signal
                is the creep TOWARD the floor.  In bf16 this is the
                underflow creep stas00's detector hunted: gradients
                that quietly flush before the loss scale notices.
``saturated_frac``  fraction of finite elements with |x| >=
                0.99 * finfo(dtype).max — one multiply from Inf.
``size``        element count (so consumers can re-weight aggregates)

All stats mask nonfinite values OUT of the other seven — a single NaN
must show up as ``nonfinite=1``, not poison absmax/rms into NaN and
erase the very signal the probe exists to carry.

The thresholds (``tiny``/``max``) come from the tensor's OWN dtype at
trace time, so a bf16 residual and an fp32 master grad are each judged
against their real representable range.
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

#: field order of the stat vector; index with STAT_FIELDS.index(name)
STAT_FIELDS = ("nonfinite", "absmax", "min_nonzero", "rms", "zero_frac",
               "subnormal_frac", "saturated_frac", "size")

#: saturation margin: |x| within 1% of finfo.max counts as saturated
SATURATION_FRAC = 0.99

#: underflow margin: nonzero |x| within 2**8 of finfo.tiny counts as
#: underflow creep (true subnormals are FTZ-flushed before we see them)
UNDERFLOW_MARGIN_BITS = 8


def tensor_stats(x: jnp.ndarray) -> jnp.ndarray:
    """``[8]`` fp32 stat vector for ``x`` (any shape, any float dtype).

    Pure jnp — safe inside jit/scan/checkpoint.  Integer/bool inputs are
    cast to fp32 (their stats are still meaningful: zero fraction,
    absmax); the subnormal/saturation thresholds then use fp32's range.
    """
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    fi = jnp.finfo(dtype)
    xf = x.astype(jnp.float32).reshape(-1)
    n = xf.size
    finite = jnp.isfinite(xf)
    nonfinite = jnp.sum(~finite).astype(jnp.float32)
    # |x| with nonfinite masked to 0 — keeps every reduction finite
    a = jnp.where(finite, jnp.abs(xf), 0.0)
    n_finite = jnp.maximum(jnp.sum(finite).astype(jnp.float32), 1.0)
    absmax = jnp.max(a) if n else jnp.float32(0.0)
    nz = finite & (a > 0.0)
    n_nz = jnp.sum(nz).astype(jnp.float32)
    min_nonzero = jnp.min(jnp.where(nz, a, jnp.inf))
    min_nonzero = jnp.where(jnp.isfinite(min_nonzero), min_nonzero, 0.0)
    # rms scaled by absmax so the sum of squares can't overflow fp32
    # even for tensors sitting at the top of bf16/fp32 range
    scale = jnp.maximum(absmax, jnp.float32(1e-30))
    rms = scale * jnp.sqrt(
        jnp.sum(jnp.where(finite, jnp.square(a / scale), 0.0)) / n_finite)
    zero_frac = jnp.sum(finite & (a == 0.0)).astype(jnp.float32) / n_finite
    tiny = jnp.float32(float(fi.tiny) * 2.0 ** UNDERFLOW_MARGIN_BITS)
    subnormal = jnp.sum(nz & (a < tiny)).astype(jnp.float32) \
        / jnp.maximum(n_nz, 1.0)
    sat = jnp.sum(finite
                  & (a >= jnp.float32(SATURATION_FRAC * float(fi.max)))
                  ).astype(jnp.float32) / n_finite
    return jnp.stack([nonfinite, absmax, min_nonzero, rms, zero_frac,
                      subnormal, sat, jnp.float32(n)])


def stats_to_dict(vec) -> Dict[str, float]:
    """``[8]`` vector (device array / np / list) → named host floats."""
    arr = np.asarray(vec, dtype=np.float64).reshape(-1)
    return {name: float(arr[i]) for i, name in enumerate(STAT_FIELDS)}


def summarize_tree(named: Dict[str, "np.ndarray"]) -> Dict[str, Dict[str, float]]:
    """{probe name: [8] vector} → {probe name: {field: float}} — the
    host-side decode step after the step's aux pytree lands."""
    return {name: stats_to_dict(vec) for name, vec in named.items()}


def first_nonfinite(per_probe: Dict[str, Dict[str, float]],
                    order: List[str]) -> str:
    """Name of the FIRST probe (in ``order`` = program order) whose
    nonfinite count is > 0, or ``""`` when everything is finite."""
    for name in order:
        st = per_probe.get(name)
        if st and st.get("nonfinite", 0.0) > 0.0:
            return name
    return ""
