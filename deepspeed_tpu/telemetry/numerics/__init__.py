"""Numerics observability plane (ISSUE 18) — per-layer tensor health,
NaN origin bisection, and MoE routing telemetry INSIDE the jitted step.

Three pieces:

* :mod:`.stats` — the 8-scalar per-tensor stat vector
  (:func:`tensor_stats`) every probe folds its tensor into, computed
  in-graph: nonfinite count, abs-max, smallest nonzero, rms, zero
  fraction, subnormal/underflow fraction, dtype-saturation fraction,
  size.
* :mod:`.probe` — the :func:`probe` tag models call (IDENTITY when the
  plane is off — same jaxpr, zero recompiles), the trace-time
  :class:`Collector`, the :func:`scan_mark`/:func:`scan_drain`/
  :func:`scan_collect` bracket that threads per-layer stats out of a
  ``lax.scan``-stacked decoder as scan ``ys``, the :func:`moe_stats`
  gate-telemetry hook, and the host-side :func:`decode`/
  :func:`summarize` pair.
* :mod:`.forensics` — the NaN origin bisection: on a non-finite loss
  the engine re-runs the forward with all probes on and this module
  turns the capture into a :class:`NonFiniteOriginReport` + a
  ``numerics.json`` bundle side file NAMING the first bad layer.

Read side: ``python -m deepspeed_tpu.telemetry numerics {show,top,diff}``
(:mod:`.cli`).
"""

from __future__ import annotations

from .forensics import (NUMERICS_JSON, NonFiniteOriginReport, build_report,
                        report_from_capture, write_numerics_json)
from .probe import (Collector, active, collecting, combine_stats, decode,
                    grad_stats, moe_stats, probe, reset, scan_collect,
                    scan_drain, scan_mark, summarize, suppressed)
from .stats import (STAT_FIELDS, first_nonfinite, stats_to_dict,
                    summarize_tree, tensor_stats)

__all__ = [
    "STAT_FIELDS", "tensor_stats", "stats_to_dict", "summarize_tree",
    "first_nonfinite",
    "Collector", "collecting", "suppressed", "active", "reset", "probe",
    "moe_stats", "scan_mark", "scan_drain", "scan_collect",
    "combine_stats", "grad_stats", "decode", "summarize",
    "NUMERICS_JSON", "NonFiniteOriginReport", "build_report",
    "write_numerics_json", "report_from_capture",
]
