"""Store-clock synchronization — one timeline for N processes.

Every cross-process artifact in this plane (heartbeat staleness, the
metrics rollup, merged traces) needs ONE clock, and the rendezvous
store's monotonic clock is already that clock for heartbeats (the
server stamps ``op=hb`` itself).  Traces can't be server-stamped — a
span's start/end happen on the worker — so each client ESTIMATES its
offset to the store clock the classic NTP way: send ``now``, halve the
round trip, take the best (minimum-RTT) of a few probes::

    t0 = perf_counter()          # local send
    s  = client.now()            # store monotonic
    t1 = perf_counter()          # local receive
    offset = s - (t0 + t1) / 2   # store_time ~= perf_counter() + offset

The estimate is re-taken **per reconnect generation**: a store restart
(``srv/gen`` change) resets the store's monotonic epoch, and a healed
partition may have let the estimate go stale — both invalidate the old
offset, so :func:`maybe_sync_clock` keys the cached estimate on
``(srv_gen, reconnects)`` and refreshes exactly when either moves.

On every successful estimate the process-global span tracer is stamped
(:meth:`SpanTracer.set_clock_sync`), so the Chrome-trace export — and
therefore every debug bundle's ``trace.json`` — carries the mapping
from its private ``perf_counter`` timebase to the shared store clock.
``telemetry collect`` uses exactly that mapping to merge N hosts'
traces into one clock-aligned ``cluster_trace.json``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..utils.logging import debug_once

#: probes per estimate — the minimum-RTT sample wins (queueing delay
#: only ever ADDS to a round trip, so the fastest probe is the truest)
DEFAULT_PROBES = 5


class ClockSync:
    """Cached store-clock offset for this process (thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.offset_s: Optional[float] = None
        self.rtt_s: Optional[float] = None
        #: the (srv/gen, client reconnect count) the estimate was taken
        #: under — either moving invalidates it
        self._key: Optional[tuple] = None
        self.estimates = 0

    @property
    def synced(self) -> bool:
        return self.offset_s is not None

    def status(self) -> Dict[str, Any]:
        """JSON-able summary (bundle context, rollup meta)."""
        with self._lock:
            return {"synced": self.offset_s is not None,
                    "offset_s": self.offset_s, "rtt_s": self.rtt_s,
                    "estimates": self.estimates,
                    "generation": (self._key[0] if self._key else None)}

    def invalidate(self) -> None:
        with self._lock:
            self._key = None

    def reset(self) -> None:
        """Test isolation: forget the estimate entirely."""
        with self._lock:
            self.offset_s = None
            self.rtt_s = None
            self._key = None
            self.estimates = 0

    # ------------------------------------------------------------------

    @staticmethod
    def _client_key(client: Any) -> tuple:
        return (getattr(client, "_gen", None),
                int(getattr(client, "reconnects", 0)))

    def estimate(self, client: Any, probes: int = DEFAULT_PROBES
                 ) -> Dict[str, float]:
        """Take a fresh estimate against ``client`` (raises the client's
        ConnectionError family when the store is down — callers on
        heartbeat paths guard, same as any other store call).  The
        validity key is snapshotted BEFORE probing and re-checked after:
        a store restart mid-estimate would otherwise blend two server
        epochs into one offset and cache it under the post-restart key,
        leaving wrong-epoch trace lanes marked aligned forever — a moved
        key discards the probes and re-takes once, then raises so the
        next tick starts clean."""
        for _attempt in range(2):
            key = self._client_key(client)
            best_off, best_rtt = None, None
            for _ in range(max(1, int(probes))):
                t0 = time.perf_counter()
                store_now = float(client.now())
                t1 = time.perf_counter()
                rtt = t1 - t0
                if best_rtt is None or rtt < best_rtt:
                    best_rtt = rtt
                    best_off = store_now - (t0 + t1) / 2.0
            if self._client_key(client) != key:
                continue  # generation/reconnect moved mid-probe: re-take
            with self._lock:
                self.offset_s = best_off
                self.rtt_s = best_rtt
                self._key = key
                self.estimates += 1
            return {"offset_s": best_off, "rtt_s": best_rtt}
        raise ConnectionError(
            "store generation kept moving during the clock estimate; "
            "retrying on the next tick")

    def needs_estimate(self, client: Any) -> bool:
        key = self._client_key(client)
        with self._lock:
            return self._key is None or self._key != key


_sync = ClockSync()


def get_clock_sync() -> ClockSync:
    return _sync


def maybe_sync_clock(client: Any, tracer: Any = None,
                     node_id: Optional[str] = None) -> Optional[ClockSync]:
    """(Re-)estimate the store-clock offset when needed — first call,
    store restart (``srv/gen`` moved), or a reconnect after an outage —
    and stamp the span tracer so trace exports carry the mapping.
    Returns the sync when an estimate is HELD (fresh or cached), None
    when the store could not be reached for a needed estimate."""
    sync = _sync
    if not sync.needs_estimate(client):
        return sync
    try:
        est = sync.estimate(client)
    except (OSError, ConnectionError, ValueError) as e:
        # store down mid-estimate: keep whatever estimate we had (a
        # stale offset beats none for an already-exported trace), retry
        # on the next tick
        debug_once("clocksync/estimate",
                   f"store clock estimate failed ({e!r}); retrying on "
                   f"the next healthy tick")
        return sync if sync.synced else None
    if tracer is None:
        from . import get_telemetry

        tracer = get_telemetry().tracer
    try:
        tracer.set_clock_sync(
            offset_s=est["offset_s"], rtt_s=est["rtt_s"],
            generation=getattr(client, "_gen", None), node_id=node_id)
    except Exception as e:  # a tracer without the hook (test double)
        debug_once("clocksync/tracer_stamp",
                   f"tracer clock stamp failed ({e!r})")
    from . import get_telemetry

    tel = get_telemetry()
    tel.inc_counter("telemetry/clock_syncs_total",
                    help="store-clock offset estimates taken")
    tel.set_gauge("telemetry/clock_offset_s", float(est["offset_s"] or 0.0),
                  help="estimated local->store clock offset (seconds)")
    return sync
