"""Step anatomy — where the device time inside the jitted step goes.

Two complementary sources, joined at capture time:

* the **cost ledger** (:mod:`.ledger`) — compile-time FLOPs / HBM
  bytes / collective bytes per tracked program, harvested once from the
  AOT executable's cost model (zero steady-state overhead), each with a
  roofline verdict against the device peak table;
* the **trace timeline** (:mod:`.capture` + :mod:`.classify`) — N fenced
  steps under ONE shared profiler session, every device-lane op
  classified into compute / exposed-collective / overlapped-collective /
  host-sync buckets, attributing ≥90% of the fenced step time.

Surfaces: ``StepRecord.extra['anatomy']``, the debug-bundle
``context.anatomy``, per-host comm/overlap gauges in the cluster rollup
and manifest, ``python -m deepspeed_tpu.telemetry anatomy`` for humans,
and sentinel-gated ``comm_fraction`` / ``overlap_hiding_frac`` in bench
artifacts.
"""

from .classify import (BUCKETS, HOST_SYNC_PATTERNS, bucket_of,
                       classify_events, format_anatomy)
from .ledger import (CostLedger, comm_bytes_from_hlo,
                     configure_cost_ledger, get_cost_ledger)
from .capture import capture_step_anatomy, probe_program

__all__ = [
    "BUCKETS", "HOST_SYNC_PATTERNS", "CostLedger", "bucket_of",
    "capture_step_anatomy", "classify_events", "comm_bytes_from_hlo",
    "configure_cost_ledger", "format_anatomy", "get_cost_ledger",
    "probe_program",
]
