"""Compile-time cost ledger — FLOPs / HBM bytes / collective bytes per
tracked program, harvested from the AOT executable's compiler cost model.

The CompileTracker holds the ``compiled`` handle exactly once, at
compile time — ``cost_analysis()`` there costs the steady state nothing
(the original flops_profiler re-derives costs with live module hooks on
every profiled step; this ledger is the zero-overhead XLA-native
replacement for tracked jit sites).

Each entry carries a roofline verdict against the device peak table
(:func:`~...profiling.flops_profiler.peak_for_device`):

* arithmetic intensity AI = flops / hbm_bytes
* predicted step time = max(flops/peak_flops, hbm/hbm_bw, comm/ici_bw)
* verdict = whichever component dominates (compute / hbm / comm bound)

Provenance is explicit: ``measured`` when the numbers came from the
compiler's cost model, ``estimated`` when the backend has no cost model
and the ledger fell back to analytic estimates (memory analysis + HLO
text scan).  The peak table's own source (``spec`` vs
``backend_default``) is recorded alongside — a CPU-backend roofline is
an estimate twice over and says so.
"""

from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional

from ...profiling.flops_profiler import DevicePeak, peak_for_device
from ..flight_recorder import get_flight_recorder

#: element sizes for HLO shape strings (collective comm-bytes scan)
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

#: HLO result shapes feeding a collective instruction, e.g.
#: ``%ar = f32[1024,512]{1,0} all-reduce(...)``
_COLLECTIVE_HLO_RE = re.compile(
    r"=\s*(?:\(?)([a-z0-9]+)\[([\d,]*)\][^=]*?\b"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)\b")


def _shape_bytes(dtype: str, dims: str) -> int:
    elems = 1
    for d in dims.split(","):
        if d.strip():
            elems *= int(d)
    return elems * _DTYPE_BYTES.get(dtype, 4)


def comm_bytes_from_hlo(hlo_text: str) -> int:
    """Total bytes moved by collective instructions, from the optimized
    HLO text — an analytic estimate (each collective counted once at its
    result shape; all-reduce ring traffic is ~2x this, but the roofline
    only needs the right order of magnitude)."""
    total = 0
    for m in _COLLECTIVE_HLO_RE.finditer(hlo_text):
        total += _shape_bytes(m.group(1), m.group(2))
    return total


def _cost_dict(compiled: Any) -> Dict[str, float]:
    """Normalize ``compiled.cost_analysis()`` across jax versions (older
    releases return ``[dict]`` per module, newer a flat dict)."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


class CostLedger:
    """Per-program compile-time costs with roofline verdicts.

    Thread-safe; the global instance is wired into the CompileTracker by
    :func:`configure_cost_ledger` and read by the anatomy capture, the
    debug bundle, and the tuning tie-breaker.
    """

    def __init__(self, peak: Optional[DevicePeak] = None):
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._peak = peak
        self._last_capture: Optional[Dict[str, Any]] = None

    # -- peaks -------------------------------------------------------------

    @property
    def peak(self) -> DevicePeak:
        if self._peak is None:
            self._peak = peak_for_device()
        return self._peak

    # -- harvest -----------------------------------------------------------

    def harvest(self, site: str, program: int, compiled: Any) -> None:
        """CompileTracker cost-harvester hook: pull the compiler cost
        model out of a fresh AOT executable.  Never raises (the tracker
        wraps it anyway); degrades to analytic estimates when the
        backend exposes no cost model."""
        flops = hbm = comm = 0.0
        provenance = "measured"
        try:
            cost = _cost_dict(compiled)
        except Exception:
            cost = {}
        flops = float(cost.get("flops", 0.0) or 0.0)
        hbm = float(cost.get("bytes accessed", 0.0) or 0.0)
        if flops <= 0.0 and hbm <= 0.0:
            provenance = "estimated"
            hbm = self._estimate_bytes(compiled)
        comm = self._comm_bytes(compiled)
        self.record(site, program, flops=flops, hbm_bytes=hbm,
                    comm_bytes=comm, provenance=provenance)

    def _estimate_bytes(self, compiled: Any) -> float:
        # no cost model: memory analysis still knows the buffer sizes
        # every step must at least touch once
        try:
            mem = compiled.memory_analysis()
            return float(
                getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
                + getattr(mem, "temp_size_in_bytes", 0))
        except Exception:
            return 0.0

    def _comm_bytes(self, compiled: Any) -> float:
        # cost models don't split out collective traffic — scan the
        # optimized HLO for collective result shapes instead
        try:
            return float(comm_bytes_from_hlo(compiled.as_text()))
        except Exception:
            return 0.0

    def record(self, site: str, program: int, flops: float = 0.0,
               hbm_bytes: float = 0.0, comm_bytes: float = 0.0,
               provenance: str = "estimated") -> Dict[str, Any]:
        """Record one program's costs (public so offline tools and tests
        can feed entries without an executable)."""
        peak = self.peak
        ai = flops / hbm_bytes if hbm_bytes > 0 else 0.0
        t_compute = flops / peak.flops_per_s if peak.flops_per_s else 0.0
        t_hbm = (hbm_bytes / peak.hbm_bytes_per_s
                 if peak.hbm_bytes_per_s else 0.0)
        t_comm = (comm_bytes / peak.ici_bytes_per_s
                  if peak.ici_bytes_per_s else 0.0)
        predicted_s = max(t_compute, t_hbm, t_comm)
        if predicted_s <= 0.0:
            verdict = "unknown"
        elif t_comm >= t_compute and t_comm >= t_hbm:
            verdict = "comm-bound"
        elif t_compute >= t_hbm:
            verdict = "compute-bound"
        else:
            verdict = "hbm-bound"
        entry = {
            "site": site, "program": int(program),
            "flops": float(flops), "hbm_bytes": float(hbm_bytes),
            "comm_bytes": float(comm_bytes),
            "arithmetic_intensity": round(ai, 3),
            "critical_intensity": round(peak.critical_intensity, 3),
            "predicted_us": round(predicted_s * 1e6, 3),
            "predicted_breakdown_us": {
                "compute": round(t_compute * 1e6, 3),
                "hbm": round(t_hbm * 1e6, 3),
                "comm": round(t_comm * 1e6, 3)},
            "verdict": verdict,
            "provenance": provenance,
            "peak": peak.to_dict(),
        }
        # profiler-plane calibration (ISSUE 20): when a fleet capture
        # has measured this device kind, ground the analytic prediction
        # in the persisted measured/modeled factors.  compute and hbm
        # share a factor — the trace cannot split them per-op.
        from ..profiler.calibration import calibration_scale

        f_comp = calibration_scale(peak.kind, "compute")
        f_comm = calibration_scale(peak.kind, "collective")
        if f_comp != 1.0 or f_comm != 1.0:
            cal_s = max(t_compute * f_comp, t_hbm * f_comp,
                        t_comm * f_comm)
            entry["calibrated_us"] = round(cal_s * 1e6, 3)
            entry["calibration"] = {"compute": round(f_comp, 4),
                                    "collective": round(f_comm, 4)}
        with self._lock:
            self._entries[f"{site}#{int(program)}"] = entry
        return entry

    # -- queries -----------------------------------------------------------

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def entry_for(self, site: str, program: Optional[int] = None
                  ) -> Optional[Dict[str, Any]]:
        """Latest entry for a jit site (highest program id wins when the
        site recompiled), or the exact ``site#program`` when given."""
        with self._lock:
            if program is not None:
                e = self._entries.get(f"{site}#{int(program)}")
                return dict(e) if e else None
            best = None
            for e in self._entries.values():
                if e["site"] == site and (
                        best is None or e["program"] > best["program"]):
                    best = e
            return dict(best) if best else None

    def top(self, k: int = 5) -> List[Dict[str, Any]]:
        """The k costliest programs by predicted step time."""
        rows = self.entries()
        rows.sort(key=lambda e: -e["predicted_us"])
        return rows[:max(int(k), 0)]

    def summary(self, top_k: int = 5) -> Dict[str, Any]:
        rows = self.top(top_k)
        return {
            "programs": len(self.entries()),
            "peak": self.peak.to_dict(),
            "top": rows,
            "roofline_top": rows[0]["verdict"] if rows else None,
        }

    def headroom(self, site: str, measured_us: float,
                 program: Optional[int] = None) -> Optional[float]:
        """Roofline headroom for a site: ``1 - predicted/measured``.
        Near 0 means the program runs at its hardware limit; large
        positive means unexplained stall time.  None when the site is
        unknown or either time is non-positive."""
        e = self.entry_for(site, program)
        if not e or measured_us <= 0:
            return None
        # the measurement-grounded prediction wins once a fleet capture
        # has calibrated this device kind
        predicted = float(e.get("calibrated_us") or e["predicted_us"])
        if predicted <= 0:
            return None
        return round(1.0 - min(predicted / measured_us, 1.0), 4)

    # -- last anatomy capture (bundle/manifest surface) --------------------

    def set_last_capture(self, summary: Dict[str, Any]) -> None:
        with self._lock:
            self._last_capture = dict(summary)

    def last_capture(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._last_capture) if self._last_capture else None

    def context(self) -> Dict[str, Any]:
        """Debug-bundle context provider payload (compact: no event
        lists, capped program table)."""
        cap = self.last_capture()
        if cap:
            cap.pop("events", None)
        return {"cost_ledger": self.summary(), "last_capture": cap}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._last_capture = None
            self._peak = None


_default = CostLedger()


def get_cost_ledger() -> CostLedger:
    return _default


def configure_cost_ledger(tracker: Any = None, recorder: Any = None
                          ) -> CostLedger:
    """Wire the global ledger into the compile tracker (harvest every
    AOT compile) and the flight recorder (``context.anatomy`` in every
    debug bundle)."""
    if tracker is not None:
        # registering twice would double-harvest; the tracker keeps the
        # callable identity, so guard by function identity
        if _default.harvest not in getattr(tracker, "_cost_harvesters", []):
            tracker.add_cost_harvester(_default.harvest)
    rec = recorder if recorder is not None else get_flight_recorder()
    rec.register_context("anatomy", _default.context)
    return _default
