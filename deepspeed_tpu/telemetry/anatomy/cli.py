"""``python -m deepspeed_tpu.telemetry anatomy {show,capture,diff}``.

* ``show``    — render a saved ``anatomy.json``: bucket decomposition,
  comm fraction, overlap hiding, roofline predicted-vs-measured for the
  top-K programs.  ``--export-perfetto`` re-emits the capture's device
  events as a chrome-trace JSON loadable in Perfetto/``chrome://tracing``.
* ``capture`` — run the built-in probe program under ONE shared profiler
  session and write ``anatomy.json`` (``--dry-run``: tiny shapes, one
  step — the CI smoke path).  Works on whatever backend is present; on
  CPU the roofline is marked against backend-default peaks.
* ``diff``    — two captures: bucket deltas and the comm-fraction /
  overlap movement between them (the "did my overlap change land"
  question).
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
from typing import Any, Dict, Optional

from .classify import BUCKETS, format_anatomy


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


def _load_anatomy(path: str) -> Optional[Dict[str, Any]]:
    """Accept an anatomy.json file, or a dir containing one (possibly
    nested — capture writes into the trace dir)."""
    if os.path.isfile(path):
        with open(path) as f:
            return json.load(f)
    if os.path.isdir(path):
        direct = os.path.join(path, "anatomy.json")
        if os.path.isfile(direct):
            with open(direct) as f:
                return json.load(f)
        for root, _dirs, files in os.walk(path):
            if "anatomy.json" in files:
                with open(os.path.join(root, "anatomy.json")) as f:
                    return json.load(f)
    return None


def _print_roofline(summary: Dict[str, Any]) -> None:
    rows = summary.get("roofline") or []
    if not rows:
        return
    peak = summary.get("peak") or {}
    print(f"  roofline (peak: {peak.get('kind', '?')}, "
          f"source {peak.get('source', '?')}):")
    print(f"    {'SITE':<28} {'VERDICT':<14} {'AI':>8} "
          f"{'PRED_US':>10} {'MEAS_US':>10} {'HEADROOM':>9} PROV")
    for r in rows:
        meas = r.get("measured_us")
        head = r.get("headroom")
        print(f"    {r['site']:<28} {r['verdict']:<14} "
              f"{r['arithmetic_intensity']:>8.2f} "
              f"{r['predicted_us']:>10.1f} "
              f"{(f'{meas:.1f}' if meas is not None else '-'):>10} "
              f"{(f'{head:.3f}' if head is not None else '-'):>9} "
              f"{r['provenance']}")


def _export_perfetto(summary: Dict[str, Any], out: str) -> int:
    events = summary.get("events") or []
    if not events:
        return _fail("this anatomy.json carries no event sample "
                     "(older capture?) — nothing to export")
    lanes = sorted({e.get("lane", "?") for e in events})
    pid_of = {lane: i + 1 for i, lane in enumerate(lanes)}
    te = [{"ph": "M", "name": "process_name", "pid": pid_of[lane],
           "args": {"name": lane}} for lane in lanes]
    for e in events:
        te.append({"ph": "X", "pid": pid_of.get(e.get("lane", "?"), 0),
                   "tid": 0, "ts": e["ts_us"], "dur": e["dur_us"],
                   "name": e["name"]})
    doc = {"traceEvents": te,
           "displayTimeUnit": "ms",
           "metadata": {"source": "deepspeed_tpu anatomy capture"}}
    opener = gzip.open if out.endswith(".gz") else open
    with opener(out, "wt") as f:
        json.dump(doc, f)
    trunc = summary.get("events_truncated") or 0
    print(f"perfetto trace written: {out} ({len(events)} events"
          + (f", {trunc} truncated from the capture" if trunc else "")
          + ")")
    return 0


def cmd_anatomy(args: argparse.Namespace) -> int:
    if args.anatomy_cmd == "show":
        summary = _load_anatomy(args.path)
        if summary is None:
            return _fail(f"{args.path}: no anatomy.json found "
                         f"(run `anatomy capture` or "
                         f"engine.capture_anatomy first)")
        print(f"anatomy: {args.path}")
        print(format_anatomy(summary))
        _print_roofline(summary)
        if getattr(args, "export_perfetto", None):
            return _export_perfetto(summary, args.export_perfetto)
        return 0

    if args.anatomy_cmd == "capture":
        # import here: capture needs jax; show/diff must work anywhere
        from .capture import capture_step_anatomy, probe_program
        from .ledger import get_cost_ledger

        fn, fargs = probe_program(dry_run=args.dry_run)
        try:  # the probe is a plain jit, not a tracked site — harvest
            # its AOT executable by hand so the roofline join has costs
            get_cost_ledger().harvest("anatomy/probe", 0,
                                      fn.lower(*fargs).compile())
        except Exception as exc:
            from ...utils.logging import debug_once

            debug_once("anatomy/probe_harvest",
                       f"probe AOT harvest failed (capture proceeds "
                       f"without a roofline join): {exc!r}")
        steps = 1 if args.dry_run else args.steps
        summary = capture_step_anatomy(
            fn, *fargs, steps=steps, trace_dir=args.out or None,
            site="anatomy/probe", feed_census=args.census)
        print(format_anatomy(summary))
        _print_roofline(summary)
        if summary.get("path"):
            print(f"written: {summary['path']}")
        return 0

    # diff
    a, b = _load_anatomy(args.a), _load_anatomy(args.b)
    if a is None or b is None:
        return _fail("diff needs two anatomy.json files/dirs")
    print(f"A: {args.a}\nB: {args.b}")
    wa = float(a.get("window_us") or 0.0)
    wb = float(b.get("window_us") or 0.0)
    print(f"window_us: {wa:.1f} -> {wb:.1f} ({wb - wa:+.1f})")
    for key in BUCKETS:
        va = float(a.get(f"{key}_us") or 0.0)
        vb = float(b.get(f"{key}_us") or 0.0)
        if va or vb:
            print(f"  {key}_us: {va:.1f} -> {vb:.1f} ({vb - va:+.1f})")
    for key in ("comm_fraction", "overlap_hiding_frac",
                "attributed_frac"):
        va, vb = a.get(key), b.get(key)
        if va is None and vb is None:
            continue
        sa = f"{va:.3f}" if va is not None else "-"
        sb = f"{vb:.3f}" if vb is not None else "-"
        print(f"  {key}: {sa} -> {sb}")
    ra = {r["site"]: r for r in a.get("roofline") or []}
    rb = {r["site"]: r for r in b.get("roofline") or []}
    for site in sorted(set(ra) | set(rb)):
        va, vb = ra.get(site), rb.get(site)
        print(f"  roofline {site}: "
              f"{va['verdict'] if va else '-'} -> "
              f"{vb['verdict'] if vb else '-'}")
    return 0


def add_anatomy_parser(sub: Any) -> None:
    a = sub.add_parser("anatomy",
                       help="step anatomy: roofline + comm/compute "
                            "attribution inside the jitted step")
    asub = a.add_subparsers(dest="anatomy_cmd", required=True)

    sh = asub.add_parser("show", help="render a saved anatomy capture")
    sh.add_argument("path", help="anatomy.json, or a dir containing one")
    sh.add_argument("--export-perfetto", default="", metavar="OUT",
                    help="also write the capture's device events as a "
                         "chrome-trace JSON (.json or .json.gz) for "
                         "Perfetto")
    sh.set_defaults(fn=cmd_anatomy)

    cp = asub.add_parser("capture",
                         help="capture the built-in probe program on "
                              "the current backend and write "
                              "anatomy.json")
    cp.add_argument("--steps", type=int, default=3)
    cp.add_argument("--out", default="",
                    help="trace/output dir (default: temp dir)")
    cp.add_argument("--dry-run", action="store_true",
                    help="tiny shapes, one step — the CI smoke path")
    cp.add_argument("--census", action="store_true",
                    help="also feed the exec-order census from the "
                         "same (single) profiler session")
    cp.set_defaults(fn=cmd_anatomy)

    df = asub.add_parser("diff", help="compare two anatomy captures")
    df.add_argument("a")
    df.add_argument("b")
    df.set_defaults(fn=cmd_anatomy)
