"""Device-lane timeline classifier — where the fenced step time went.

Input: the full device-lane op events of a profiler trace window
(``profiling.collective_trace.parse_device_events``).  Output: a
wall-time decomposition of that window into four buckets:

* ``compute``          — some compute op was running (collectives may
  be running concurrently underneath; that concurrent collective time
  is *hidden* and lands in ``coll_overlapped`` without adding wall)
* ``coll_exposed``     — only collectives were running: the step was
  WAITING on the network (this is the comm-bound share of wall time)
* ``host_sync``        — infeed/outfeed/callback ops: the device was
  waiting on the host
* ``idle``             — no device activity at all inside the window
  (host-side gaps between dispatches; reported separately but counted
  toward host-caused time in ``host_sync_us`` totals)

The sweep is exact: ``compute + coll_exposed + host_sync + idle ==
window`` by construction, so the only attribution loss vs the FENCED
wall clock is trace coverage — ``attributed_frac`` reports it, and the
capture's acceptance floor (≥ 90%) is asserted on exactly that number.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ...profiling.collective_trace import COLLECTIVE_PATTERNS

#: op-name substrings that mean "the device is waiting on the host"
HOST_SYNC_PATTERNS = (
    "infeed", "outfeed", "host-callback", "host_callback", "callback",
    "transferto", "transferfrom", "h2d", "d2h",
)

#: bucket keys in render order
BUCKETS = ("compute", "coll_exposed", "coll_overlapped", "host_sync",
           "idle")


def bucket_of(name: str,
              collective_patterns: Sequence[str] = COLLECTIVE_PATTERNS,
              host_patterns: Sequence[str] = HOST_SYNC_PATTERNS) -> str:
    """The activity class of one device op: ``collective`` /
    ``host_sync`` / ``compute`` (everything else XLA ran)."""
    low = name.lower()
    if any(p in low for p in collective_patterns):
        return "collective"
    if any(p in low for p in host_patterns):
        return "host_sync"
    return "compute"


def _merge_intervals(iv: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for s, e in iv[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def classify_events(events: List[Dict[str, Any]],
                    wall_us: Optional[float] = None,
                    steps: int = 1,
                    top_k: int = 8) -> Dict[str, Any]:
    """Sweep the window and decompose wall time into buckets.

    ``events`` are ``{ts_us, dur_us, name, lane}`` device-lane ops (all
    lanes — overlap is visible precisely because TPU runs collectives on
    a separate stream/lane from compute).  ``wall_us`` is the host-fenced
    wall time of the captured steps; when given, ``attributed_frac`` is
    window/wall (how much of the fenced time the trace explains).
    """
    steps = max(int(steps), 1)
    per_class: Dict[str, List[Tuple[float, float]]] = {
        "compute": [], "collective": [], "host_sync": []}
    per_op: Dict[str, Dict[str, float]] = {}
    for ev in events:
        dur = float(ev.get("dur_us", 0.0))
        if dur <= 0:
            continue
        ts = float(ev.get("ts_us", 0.0))
        cls = bucket_of(ev.get("name", ""))
        per_class[cls].append((ts, ts + dur))
        row = per_op.setdefault(ev.get("name", "?"),
                                {"total_us": 0.0, "count": 0.0,
                                 "class": cls})
        row["total_us"] += dur
        row["count"] += 1
    merged = {c: _merge_intervals(iv) for c, iv in per_class.items()}
    empty = not any(merged.values())
    if empty:
        window = 0.0
        t0 = 0.0
    else:
        t0 = min(iv[0][0] for iv in merged.values() if iv)
        t1 = max(iv[-1][1] for iv in merged.values() if iv)
        window = t1 - t0

    # elementary-segment sweep over all class boundaries
    points = sorted({p for iv in merged.values() for s, e in iv
                     for p in (s, e)})
    buckets = {k: 0.0 for k in BUCKETS}

    def active(ivs: List[Tuple[float, float]], lo: float, hi: float) -> bool:
        # ivs are merged+sorted; binary search would be O(log n) but the
        # segment count is already O(n) — linear scan with early exit
        for s, e in ivs:
            if s >= hi:
                return False
            if e > lo:
                return True
        return False

    for lo, hi in zip(points, points[1:]):
        if hi <= lo:
            continue
        seg = hi - lo
        comp = active(merged["compute"], lo, hi)
        coll = active(merged["collective"], lo, hi)
        hsync = active(merged["host_sync"], lo, hi)
        if comp:
            buckets["compute"] += seg
            if coll:
                buckets["coll_overlapped"] += seg
        elif coll:
            buckets["coll_exposed"] += seg
        elif hsync:
            buckets["host_sync"] += seg
        else:
            buckets["idle"] += seg

    coll_total = buckets["coll_exposed"] + buckets["coll_overlapped"]
    wall = float(wall_us) if wall_us else 0.0
    attributed = min(1.0, window / wall) if wall > 0 else (
        1.0 if window > 0 else 0.0)
    top = sorted(per_op.items(), key=lambda kv: -kv[1]["total_us"])
    out: Dict[str, Any] = {
        "window_us": round(window, 1),
        "wall_us": round(wall, 1) if wall else None,
        "steps": steps,
        "lanes": len({ev.get("lane") for ev in events}),
        "events": len(events),
        "compute_us": round(buckets["compute"], 1),
        "coll_exposed_us": round(buckets["coll_exposed"], 1),
        "coll_overlapped_us": round(buckets["coll_overlapped"], 1),
        "host_sync_us": round(buckets["host_sync"], 1),
        "idle_us": round(buckets["idle"], 1),
        "comm_fraction": (round(buckets["coll_exposed"] / window, 4)
                          if window > 0 else 0.0),
        "overlap_hiding_frac": (
            round(buckets["coll_overlapped"] / coll_total, 4)
            if coll_total > 0 else None),
        "attributed_frac": round(attributed, 4),
        "top_ops": [{"name": n, "class": r["class"],
                     "total_us": round(r["total_us"], 1),
                     "count": int(r["count"])}
                    for n, r in top[:max(int(top_k), 0)]],
    }
    return out


def format_anatomy(summary: Dict[str, Any]) -> str:
    """Human rendering of one classified window (CLI ``anatomy show``)."""
    window = float(summary.get("window_us") or 0.0)
    steps = int(summary.get("steps") or 1)
    lines = []
    wall = summary.get("wall_us")
    lines.append(
        f"window: {window / 1e3:.3f} ms over {steps} step(s)"
        + (f"  (fenced wall {float(wall) / 1e3:.3f} ms, "
           f"{summary.get('attributed_frac', 0) * 100:.1f}% attributed)"
           if wall else ""))
    label = {"compute": "compute",
             "coll_exposed": "collective (exposed)",
             "coll_overlapped": "collective (overlapped, hidden)",
             "host_sync": "host sync", "idle": "idle (host gaps)"}
    for key in BUCKETS:
        us = float(summary.get(f"{key}_us") or 0.0)
        if us <= 0:
            continue
        # the overlapped bucket is concurrent with compute, so its
        # percentage is "of collective time", not "of wall"
        if key == "coll_overlapped":
            lines.append(f"  {label[key]:<32} {us / 1e3:9.3f} ms")
            continue
        pct = 100.0 * us / window if window else 0.0
        lines.append(f"  {label[key]:<32} {us / 1e3:9.3f} ms  {pct:5.1f}%")
    cf = summary.get("comm_fraction")
    oh = summary.get("overlap_hiding_frac")
    lines.append(f"  comm_fraction (exposed/wall): "
                 f"{float(cf or 0.0):.3f}")
    if oh is not None:
        lines.append(f"  overlap_hiding_frac: {float(oh):.3f}")
    ops = summary.get("top_ops") or []
    if ops:
        lines.append("  top device ops:")
        for r in ops:
            lines.append(f"    {r['name']:<40} [{r['class']}] "
                         f"{float(r['total_us']) / 1e3:8.3f} ms "
                         f"x{int(r['count'])}")
    return "\n".join(lines)
