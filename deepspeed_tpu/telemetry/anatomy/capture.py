"""Step anatomy capture — N fenced steps under ONE profiler session.

``capture_step_anatomy(step_fn, *args)`` runs the (already compiled)
step a few times inside a single ``jax.profiler.trace`` window via the
shared-session plumbing (``profiling.collective_trace``), then:

1. classifies every device-lane op into compute / exposed-collective /
   overlapped-collective / host-sync buckets (:mod:`.classify`),
2. joins the cost ledger's roofline predictions against the measured
   per-step time for the top-K programs (predicted vs measured, and the
   headroom between them),
3. optionally feeds the execution-order census from the SAME trace
   (``feed_census=True``) — never a second profiler session, and
4. writes ``anatomy.json`` (summary + a capped event sample the CLI can
   re-export as a Perfetto/chrome trace).

Because the shared session is used, an anatomy capture can itself run
nested inside someone else's trace window — it then classifies nothing
live (the files don't exist yet) and defers via ``on_session_close``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax

from ...profiling.collective_trace import (active_trace_session,
                                           feed_exec_census,
                                           on_session_close,
                                           parse_device_events,
                                           shared_trace_session)
from ...utils.logging import logger
from .classify import classify_events
from .ledger import CostLedger, get_cost_ledger

#: events kept in anatomy.json for the CLI's Perfetto export — the full
#: trace stays in the session dir; this is a browsable sample
MAX_SAVED_EVENTS = 4000


def _roofline_join(ledger: CostLedger, window_us: float, steps: int,
                   site: Optional[str], top_k: int
                   ) -> List[Dict[str, Any]]:
    """Predicted (roofline) vs measured time for the top-K programs.

    Measured per-program time is only separable when the capture ran a
    single tracked site — then measured = window/steps for that site's
    entry; other programs report predictions only."""
    measured_step_us = window_us / max(steps, 1) if window_us > 0 else 0.0
    rows = []
    top = ledger.top(top_k)
    if site is not None and not any(e["site"] == site for e in top):
        e = ledger.entry_for(site)
        if e:
            top = [e] + top[:max(top_k - 1, 0)]
    for e in top:
        row = {k: e[k] for k in ("site", "program", "flops", "hbm_bytes",
                                 "comm_bytes", "arithmetic_intensity",
                                 "predicted_us", "verdict", "provenance")}
        if site is None or e["site"] == site:
            row["measured_us"] = round(measured_step_us, 1)
            row["headroom"] = ledger.headroom(
                e["site"], measured_step_us, e["program"])
        else:
            row["measured_us"] = None
            row["headroom"] = None
        rows.append(row)
    return rows


def capture_step_anatomy(step_fn: Callable[..., Any], *args,
                         steps: int = 2,
                         trace_dir: Optional[str] = None,
                         out_path: Optional[str] = None,
                         top_k: int = 5,
                         site: Optional[str] = None,
                         ledger: Optional[CostLedger] = None,
                         feed_census: bool = False,
                         warmup: bool = True,
                         **kwargs) -> Dict[str, Any]:
    """Trace ``steps`` fenced executions of ``step_fn`` and return the
    anatomy summary (classification + roofline join).

    ``site`` names the tracked jit site being captured so its roofline
    prediction can be compared against the measured step time.  With
    ``feed_census`` the exec-order census is fed from the same trace —
    the single shared profiler session serves both consumers.
    """
    steps = max(int(steps), 1)
    ledger = ledger or get_cost_ledger()
    if warmup:
        out = step_fn(*args, **kwargs)  # compile outside the window
        jax.block_until_ready(out)
    nested = active_trace_session() is not None
    with shared_trace_session(trace_dir) as tdir:
        t0 = time.perf_counter()
        for _ in range(steps):
            out = step_fn(*args, **kwargs)
        jax.block_until_ready(out)
        wall_us = (time.perf_counter() - t0) * 1e6
        if nested:
            # someone else owns the session — the trace files won't
            # exist until THEIR close; defer both feeds and return a
            # placeholder (the owner's close hook finishes the job)
            if feed_census:
                on_session_close(lambda d: feed_exec_census(d))
            on_session_close(
                lambda d: _finish_capture(d, wall_us, steps, top_k, site,
                                          ledger, out_path))
            return {"deferred": True, "trace_dir": tdir,
                    "wall_us": round(wall_us, 1), "steps": steps}
    if feed_census:
        fed = feed_exec_census(tdir)
        logger.info(f"anatomy capture: exec census fed {fed} entries "
                    f"from the shared trace")
    return _finish_capture(tdir, wall_us, steps, top_k, site, ledger,
                           out_path)


def _finish_capture(trace_dir: str, wall_us: float, steps: int,
                    top_k: int, site: Optional[str],
                    ledger: CostLedger, out_path: Optional[str]
                    ) -> Dict[str, Any]:
    events = parse_device_events(trace_dir)
    summary = classify_events(events, wall_us=wall_us, steps=steps,
                              top_k=max(top_k, 5))
    summary["trace_dir"] = trace_dir
    summary["site"] = site
    summary["roofline"] = _roofline_join(ledger, summary["window_us"],
                                         steps, site, top_k)
    summary["roofline_top"] = (summary["roofline"][0]["verdict"]
                               if summary["roofline"] else None)
    summary["peak"] = ledger.peak.to_dict()
    if summary["attributed_frac"] < 0.9 and events:
        logger.warning(
            f"anatomy capture: trace explains only "
            f"{summary['attributed_frac'] * 100:.1f}% of the fenced wall "
            f"time (floor is 90%) — host-side overhead dominates or the "
            f"backend dropped device lanes")
    ledger.set_last_capture(
        {k: v for k, v in summary.items() if k != "events"})
    path = out_path or os.path.join(trace_dir, "anatomy.json")
    try:
        doc = dict(summary)
        doc["events"] = events[:MAX_SAVED_EVENTS]
        doc["events_truncated"] = max(len(events) - MAX_SAVED_EVENTS, 0)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        summary["path"] = path
    except OSError as e:
        logger.warning(f"anatomy capture: could not write {path} ({e!r})")
    return summary


def probe_program(dry_run: bool = False):
    """A tiny self-contained program for CLI captures: matmul (+ psum
    across devices when the mesh has more than one) — enough to light up
    both the compute and collective lanes."""
    import jax.numpy as jnp

    n = 128 if dry_run else 1024
    ndev = jax.local_device_count()
    if ndev > 1:
        mesh = jax.sharding.Mesh(jax.devices()[:ndev], ("d",))

        @jax.jit
        def fn(a, b):
            out = a @ b
            return jax.lax.with_sharding_constraint(
                out, jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec()))
    else:
        @jax.jit
        def fn(a, b):
            return (a @ b).sum()

    a = jnp.ones((n, n), dtype=jnp.float32)
    b = jnp.ones((n, n), dtype=jnp.float32)
    return fn, (a, b)
