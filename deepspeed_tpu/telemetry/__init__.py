"""Unified telemetry: span tracing + metrics registry + step records.

ONE pipeline correlating what used to be fragments (ISSUE 1):

* :mod:`.tracer` — nested host-side spans (``telemetry.span("zero/...")``)
  with optional device-fence close, exported as Chrome-trace JSON that
  merges with ``profiling/collective_trace.py``'s XLA device lanes.
* :mod:`.metrics` — counters / gauges / fixed-bucket histograms with a
  JSONL event log and Prometheus text exposition.
* :mod:`.step_record` — the per-optimizer-step record the engine emits
  (device-fenced step time, throughput, loss, comm bytes, memory), the
  single source every consumer (bench, autotuner, monitors) reads.

The module-level hub is a process-global singleton, DISABLED by default:
``span()`` returns a shared no-op context manager and the counter/gauge
helpers early-return, so instrumented hot paths cost one attribute read
when telemetry is off.  Enable via the ``telemetry`` config group
(``{"telemetry": {"enabled": true, ...}}``) — wired through
``MonitorMaster`` as a fourth backend — or programmatically with
:func:`configure`.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from .collective_ledger import (CollectiveLedger, attach_collective_ledger,
                                configure_collective_ledger,
                                desync_from_heartbeats,
                                find_first_divergence,
                                format_divergence_report,
                                get_collective_ledger)
from .flight_recorder import (FlightRecorder, configure_flight_recorder,
                              get_flight_recorder, load_bundle)
from .health import HealthEvent, HealthMonitor
from .metrics import (DEFAULT_BUCKETS, Counter, Gauge, Histogram,
                      JSONLExporter, MetricsRegistry, escape_help,
                      escape_label_value, format_labels,
                      parse_prometheus_text, prom_name)
from .memory import (HBMExhaustedError, MemoryLedger,
                     configure_memory_ledger, get_memory_ledger,
                     is_oom_error, probe_device_liveness)
from .perf import (CompileTracker, GoodputLedger, configure_compile_tracker,
                   configure_goodput_ledger, get_compile_tracker,
                   get_goodput_ledger, tracked_jit)
from .clocksync import ClockSync, get_clock_sync, maybe_sync_clock
from .numerics import (NonFiniteOriginReport, STAT_FIELDS, tensor_stats)
from .rollup import (MetricsRollup, StepStream, collect_rollup,
                     configure_step_stream, get_rollup, get_step_stream,
                     push_node_telemetry, render_top, rollup_tick)
from .step_record import (StepRecord, collect_memory_stats,
                          publish_step_record)
from .tracer import NOOP_SPAN, SpanTracer, device_fence
from .watchdog import (HEARTBEAT_SCHEMA_V, HangWatchdog, WatchdogTimeout,
                       cap_heartbeat_payload, get_watchdog, set_watchdog)

__all__ = [
    "Telemetry", "StepRecord", "MetricsRegistry", "SpanTracer",
    "Counter", "Gauge", "Histogram", "JSONLExporter",
    "configure", "configure_from_config", "get_telemetry", "span",
    "publish_step_record", "collect_memory_stats", "parse_prometheus_text",
    "prom_name", "device_fence", "DEFAULT_BUCKETS",
    "FlightRecorder", "configure_flight_recorder", "get_flight_recorder",
    "load_bundle", "HealthEvent", "HealthMonitor",
    "HangWatchdog", "WatchdogTimeout", "get_watchdog", "set_watchdog",
    "CollectiveLedger", "attach_collective_ledger",
    "configure_collective_ledger", "get_collective_ledger",
    "desync_from_heartbeats", "find_first_divergence",
    "format_divergence_report",
    "escape_help", "escape_label_value", "format_labels",
    "CompileTracker", "configure_compile_tracker", "get_compile_tracker",
    "tracked_jit", "GoodputLedger", "configure_goodput_ledger",
    "get_goodput_ledger",
    "MemoryLedger", "configure_memory_ledger", "get_memory_ledger",
    "HBMExhaustedError", "is_oom_error", "probe_device_liveness",
    "MetricsRollup", "StepStream", "collect_rollup",
    "configure_step_stream", "get_rollup", "get_step_stream",
    "push_node_telemetry", "render_top", "rollup_tick",
    "ClockSync", "get_clock_sync", "maybe_sync_clock",
    "NonFiniteOriginReport", "STAT_FIELDS", "tensor_stats",
    "HEARTBEAT_SCHEMA_V", "cap_heartbeat_payload",
]


class Telemetry:
    """The hub: one tracer + one registry + output plumbing."""

    def __init__(self) -> None:
        self.enabled = False
        self.tracer = SpanTracer()
        self.registry = MetricsRegistry()
        self.output_path: Optional[str] = None
        self.chrome_trace = False
        self.prometheus = True
        self.device_fence_steps = True
        self._lock = threading.Lock()

    # -- configuration -----------------------------------------------------

    def configure(self, enabled: bool = True, output_path: str = "",
                  job_name: str = "DeepSpeedJobName", jsonl: bool = True,
                  prometheus: bool = True, chrome_trace: bool = False,
                  device_fence: bool = True,
                  max_span_events: int = 100_000) -> "Telemetry":
        with self._lock:
            self.enabled = bool(enabled)
            self.prometheus = bool(prometheus)
            self.chrome_trace = bool(chrome_trace)
            self.device_fence_steps = bool(device_fence)
            self.tracer.max_events = int(max_span_events)
            if not jsonl and self.registry.event_log is not None:
                # a reconfigure to in-memory-only must stop appending to
                # the PREVIOUS job's events.jsonl
                self.registry.event_log.close()
                self.registry.event_log = None
            if enabled and (jsonl or prometheus or chrome_trace):
                base = os.path.join(output_path or "telemetry_logs", job_name)
                self.output_path = base
                if jsonl:
                    self.registry.attach_event_log(
                        os.path.join(base, "events.jsonl"))
            elif not enabled:
                self.output_path = None
        return self

    def reset(self) -> None:
        """Test isolation: drop all metrics/spans and disable."""
        with self._lock:
            if self.registry.event_log is not None:
                self.registry.event_log.close()
            self.enabled = False
            self.output_path = None
            self.tracer = SpanTracer(self.tracer.max_events)
            self.registry = MetricsRegistry()

    # -- hot-path surface (cheap no-ops when disabled) ---------------------

    def span(self, name: str, fence: bool = False,
             args: Optional[Dict[str, Any]] = None):
        if not self.enabled:
            return NOOP_SPAN()
        return self.tracer.span(name, fence=fence, args=args)

    def inc_counter(self, name: str, v: float = 1.0, help: str = "") -> None:
        if not self.enabled:
            return
        self.registry.counter(name, help).inc(v)

    def set_gauge(self, name: str, v: float, help: str = "") -> None:
        if not self.enabled:
            return
        self.registry.gauge(name, help).set(v)

    def observe(self, name: str, v: float, help: str = "",
                buckets=DEFAULT_BUCKETS) -> None:
        if not self.enabled:
            return
        self.registry.histogram(name, help, buckets=buckets).observe(v)

    def emit_event(self, kind: str, payload: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        self.registry.emit_event(kind, payload)

    def record_step(self, rec: StepRecord) -> None:
        if not self.enabled:
            return
        publish_step_record(self.registry, rec)
        # cross-process streaming (telemetry/rollup.py): a compact copy
        # rides the bounded ring until the next publisher beat ships it
        # to rank 0's rollup (no-op unless aggregation enabled it)
        from .rollup import get_step_stream

        get_step_stream().push(rec)

    # -- export ------------------------------------------------------------

    def prometheus_text(self) -> str:
        return self.registry.prometheus_text()

    def flush(self) -> Dict[str, str]:
        """Write the configured exports (Prometheus textfile, Chrome trace)
        under ``output_path``; returns {kind: path}."""
        out: Dict[str, str] = {}
        if not (self.enabled and self.output_path):
            return out
        if self.prometheus:
            out["prometheus"] = self.registry.save_prometheus(
                os.path.join(self.output_path, "metrics.prom"))
        if self.chrome_trace:
            out["chrome_trace"] = self.tracer.save_chrome_trace(
                os.path.join(self.output_path, "trace.json"))
        return out


_default = Telemetry()


def get_telemetry() -> Telemetry:
    return _default


def configure(**kw) -> Telemetry:
    return _default.configure(**kw)


def configure_from_config(tcfg: Any) -> Telemetry:
    """Configure the hub from a ``TelemetryConfig`` (runtime/config.py)."""
    return _default.configure(
        enabled=bool(getattr(tcfg, "enabled", False)),
        output_path=getattr(tcfg, "output_path", "") or "",
        job_name=getattr(tcfg, "job_name", "DeepSpeedJobName"),
        jsonl=bool(getattr(tcfg, "jsonl", True)),
        prometheus=bool(getattr(tcfg, "prometheus", True)),
        chrome_trace=bool(getattr(tcfg, "chrome_trace", False)),
        device_fence=bool(getattr(tcfg, "device_fence", True)),
        max_span_events=int(getattr(tcfg, "max_span_events", 100_000)))


def span(name: str, fence: bool = False,
         args: Optional[Dict[str, Any]] = None):
    """Module-level convenience: ``with telemetry.span("zero/gather"): ...``"""
    return _default.span(name, fence=fence, args=args)
