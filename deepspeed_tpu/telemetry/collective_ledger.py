"""Per-rank collective ledger — the desync half of the black box.

MegaScale (arXiv:2402.15627) and PyTorch's NCCL flight recorder both
diagnose production hangs the same way: every rank keeps a monotonic
record of the collectives it issued, and the post-mortem question is
*which rank diverged, and on which collective?*  This module is that
record for this runtime:

* :class:`CollectiveLedger` — a bounded ring of ``(seq, op, bytes)``
  entries fed by ``CommsLogger.record`` (call-site/census order, which
  is deterministic per host — identical programs issue identical
  sequences).
* A separate **exec lane** (:meth:`CollectiveLedger.record_exec`) with
  its own ring and hash chain, recording EXECUTION order.  Two feeds:
  ``CommsLogger.record_exec`` probes (opt-in via ``exec_feed`` — device
  callbacks are unordered across shards, so that feed is per-host
  forensics only), and the trace-sourced census
  (``profiling.collective_trace.feed_exec_census``) which replays a
  profiler trace's device-lane collectives in timestamp order — device
  execution order of one compiled SPMD program is deterministic, so the
  trace-fed exec chain IS cross-rank comparable.  Keeping the lane
  separate means exec entries can never fork the census chain that the
  live desync detection hashes.
* A **rolling tail hash**: each entry chains
  ``h = sha1(h_prev | "op:bytes")``, so two ranks that issued the same
  sequence agree on one short string.  ``heartbeat_summary()`` returns
  ``{coll_seq, coll_hash}`` to ride the elastic rendezvous heartbeat —
  rank 0 compares payloads live (:func:`desync_from_heartbeats`) and
  flags "same seq, different hash" the tick it happens.
* :func:`find_first_divergence` — the offline analysis over full ledger
  tails (one per host, pulled from debug bundles by the aggregator):
  names the lagging rank (lowest sequence number — the host stuck in or
  before that collective) and the first mismatched collective
  (desync: ranks disagreeing on what the N-th collective even was).

The ledger is cheap enough to leave on (one lock + a sha1 over ~30
bytes per *call-site* record; trace-time census records fire once per
compile, not per step) and is a process-global singleton like the rest
of the telemetry stack — but every piece also takes explicit instances
so N in-process "hosts" can be tested in one process.
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

GENESIS_HASH = "0" * 16


def _chain(prev: str, sig: str) -> str:
    return hashlib.sha1(f"{prev}|{sig}".encode()).hexdigest()[:16]


def entry_signature(op: str, nbytes: int) -> str:
    """The cross-rank comparison key for one collective."""
    return f"{op}:{int(nbytes)}"


class CollectiveLedger:
    """Monotonic per-rank ledger of issued collectives."""

    def __init__(self, max_entries: int = 4096, tail: int = 64,
                 enabled: bool = False, exec_feed: bool = False):
        self.enabled = bool(enabled)
        #: also ingest execution-probe records (CommsLogger.record_exec).
        #: Off by default: exec callbacks are UNORDERED across device
        #: shards, so an exec-fed chain is per-host forensics only —
        #: never compare it across ranks.
        self.exec_feed = bool(exec_feed)
        self.max_entries = int(max_entries)
        #: entries embedded in snapshots/bundles (the comparison window)
        self.tail_entries = int(tail)
        self._entries: "collections.deque" = collections.deque(
            maxlen=self.max_entries)
        self._seq = 0
        self._hash = GENESIS_HASH
        #: execution-order lane: own ring + chain (see module docstring)
        self._exec_entries: "collections.deque" = collections.deque(
            maxlen=self.max_entries)
        self._exec_seq = 0
        self._exec_hash = GENESIS_HASH
        self._lock = threading.Lock()

    def configure(self, enabled: Optional[bool] = None,
                  max_entries: Optional[int] = None,
                  tail: Optional[int] = None,
                  exec_feed: Optional[bool] = None) -> "CollectiveLedger":
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if exec_feed is not None:
                self.exec_feed = bool(exec_feed)
            if tail:
                self.tail_entries = int(tail)
            if max_entries and int(max_entries) != self.max_entries:
                self.max_entries = int(max_entries)
                self._entries = collections.deque(self._entries,
                                                  maxlen=self.max_entries)
                self._exec_entries = collections.deque(
                    self._exec_entries, maxlen=self.max_entries)
        return self

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._seq = 0
            self._hash = GENESIS_HASH
            self._exec_entries.clear()
            self._exec_seq = 0
            self._exec_hash = GENESIS_HASH

    # -- recording (fed by CommsLogger.record / record_exec) ---------------

    def record(self, op: str, nbytes: int, source: str = "census") -> None:
        if not self.enabled:
            return
        sig = entry_signature(op, nbytes)
        with self._lock:
            self._seq += 1
            self._hash = _chain(self._hash, sig)
            self._entries.append({"seq": self._seq, "op": op,
                                  "bytes": int(nbytes), "hash": self._hash,
                                  "src": source, "ts": time.time()})

    def record_exec(self, op: str, nbytes: int = 0,
                    dur_us: Optional[float] = None,
                    ts_us: Optional[float] = None,
                    source: str = "exec") -> None:
        """Append to the EXEC lane (execution order).  The chain covers
        only ``(op, bytes)`` — never timings, which legitimately differ
        across ranks running the same program; two ranks that executed
        the same collective sequence agree on one ``exec_tail_hash``."""
        if not self.enabled:
            return
        sig = entry_signature(op, nbytes)
        with self._lock:
            self._exec_seq += 1
            self._exec_hash = _chain(self._exec_hash, sig)
            entry: Dict[str, Any] = {
                "seq": self._exec_seq, "op": op, "bytes": int(nbytes),
                "hash": self._exec_hash, "src": source}
            if dur_us is not None:
                entry["dur_us"] = round(float(dur_us), 3)
            if ts_us is not None:
                entry["ts_us"] = round(float(ts_us), 3)
            self._exec_entries.append(entry)

    # -- read side ---------------------------------------------------------

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def tail_hash(self) -> str:
        return self._hash

    def heartbeat_summary(self) -> Dict[str, Any]:
        """``{coll_seq, coll_hash}`` — rides the rendezvous heartbeat
        payload so rank 0 can detect desync live without pulling full
        ledgers."""
        with self._lock:
            return {"coll_seq": self._seq, "coll_hash": self._hash}

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            entries = list(self._entries)
        n = self.tail_entries if n is None else int(n)
        return entries[-n:] if n > 0 else entries

    @property
    def exec_seq(self) -> int:
        return self._exec_seq

    @property
    def exec_tail_hash(self) -> str:
        return self._exec_hash

    def exec_tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            entries = list(self._exec_entries)
        n = self.tail_entries if n is None else int(n)
        return entries[-n:] if n > 0 else entries

    def snapshot(self) -> Dict[str, Any]:
        """The flight-recorder context-provider payload: landed in every
        bundle manifest under ``context["collective_ledger"]`` so the
        cluster aggregator can run divergence analysis offline.  The
        exec lane rides along when populated — an exec-order desync
        check is :func:`find_first_divergence` over the exec tails."""
        with self._lock:
            entries = list(self._entries)[-self.tail_entries:]
            out = {"seq": self._seq, "tail_hash": self._hash,
                   "tail": entries}
            if self._exec_seq:
                out["exec_seq"] = self._exec_seq
                out["exec_tail_hash"] = self._exec_hash
                out["exec_tail"] = list(
                    self._exec_entries)[-self.tail_entries:]
            return out


# ---------------------------------------------------------------------------
# divergence analysis
# ---------------------------------------------------------------------------

def desync_from_heartbeats(payloads: Dict[str, Any]
                           ) -> Optional[Dict[str, Any]]:
    """Live check over heartbeat payloads (``{node: hbinfo}``): two ranks
    reporting the SAME ``coll_seq`` with DIFFERENT ``coll_hash`` issued
    different collectives somewhere in their history — a desync, even
    though both are still making progress.  Returns ``None`` when fewer
    than two payloads carry ledger fields."""
    seqs: Dict[str, int] = {}
    hashes: Dict[int, Dict[str, str]] = {}
    for node, info in payloads.items():
        if not (isinstance(info, dict) and "coll_seq" in info):
            continue
        s = int(info["coll_seq"])
        seqs[node] = s
        hashes.setdefault(s, {})[node] = str(info.get("coll_hash", ""))
    if len(seqs) < 2:
        return None
    out: Dict[str, Any] = {
        "per_rank_seq": seqs,
        "seq_skew": max(seqs.values()) - min(seqs.values()),
        "desync": False,
    }
    for s, by_node in sorted(hashes.items()):
        if len(by_node) >= 2 and len(set(by_node.values())) > 1:
            out["desync"] = True
            out["mismatch"] = {"seq": s, "hashes": by_node}
            break
    return out


def find_first_divergence(ledgers: Dict[str, List[Dict[str, Any]]]
                          ) -> Dict[str, Any]:
    """Offline analysis over per-rank ledger tails: name the lagging rank
    and the first mismatched collective.

    ``ledgers`` maps node id → entry list (each entry at least
    ``{seq, op, bytes}``; ``hash`` strengthens the verdict).  Tails are
    bounded rings, so only the overlapping seq window is comparable; a
    hash disagreement at the window start with identical signatures
    inside it means the divergence predates the retained window, and is
    reported as such instead of silently missed."""
    per_seq: Dict[str, int] = {}
    first: Dict[str, int] = {}
    by_seq: Dict[str, Dict[int, Dict[str, Any]]] = {}
    for node, entries in ledgers.items():
        per_seq[node] = max((int(e["seq"]) for e in entries), default=0)
        first[node] = min((int(e["seq"]) for e in entries), default=0)
        by_seq[node] = {int(e["seq"]): e for e in entries}
    report: Dict[str, Any] = {
        "per_rank_seq": per_seq,
        "lagging_rank": None,
        "seq_skew": 0,
        "first_mismatch": None,
        "desync": False,
    }
    if not per_seq:
        return report
    lo_rank = min(sorted(per_seq), key=lambda n: per_seq[n])
    report["seq_skew"] = max(per_seq.values()) - per_seq[lo_rank]
    if report["seq_skew"] > 0:
        report["lagging_rank"] = lo_rank
    # comparable window: seqs every POPULATED ledger retains, up to the
    # slowest populated rank's head — a host with no entries at all
    # (crashed pre-collective, ledger off) must not collapse the window
    # and mask a real desync between the ranks that do have data
    populated = [n for n in ledgers if by_seq[n]]
    if len(populated) < 2:
        return report
    lo = max(first[n] for n in populated)
    hi = min(per_seq[n] for n in populated)
    report["overlap"] = [lo, hi]
    for s in range(lo, hi + 1):
        sigs = {n: entry_signature(by_seq[n][s]["op"], by_seq[n][s]["bytes"])
                for n in populated if s in by_seq[n]}
        if len(sigs) >= 2 and len(set(sigs.values())) > 1:
            counts = collections.Counter(sigs.values())
            top_sig, top_n = counts.most_common(1)[0]
            if list(counts.values()).count(top_n) > 1:
                # no strict majority (e.g. a 2-rank 1-1 split): the
                # disagreement is symmetric — name every participant
                # rather than pretending one side is canonical
                divergent = sorted(sigs)
            else:
                divergent = sorted(n for n, v in sigs.items()
                                   if v != top_sig)
            report["desync"] = True
            report["first_mismatch"] = {
                "seq": s,
                "signatures": sigs,
                "divergent_ranks": divergent,
            }
            return report
    # signatures agree across the window — but do the hash chains?  A
    # disagreement here means the fork happened before the retained tail.
    for s in (lo, hi):
        hs = {n: by_seq[n][s].get("hash") for n in populated
              if s in by_seq[n] and by_seq[n][s].get("hash")}
        if len(hs) >= 2 and len(set(hs.values())) > 1:
            report["desync"] = True
            report["first_mismatch"] = {
                "seq": None,
                "note": ("hash chains disagree at seq "
                         f"{s} but retained signatures match — the "
                         "divergence predates the retained ledger window"),
                "hashes_at_seq": {str(s): hs},
            }
            return report
    return report


def format_divergence_report(report: Dict[str, Any]) -> str:
    """Human rendering of :func:`find_first_divergence` — the text the
    ``desync`` CLI prints and the cluster manifest embeds."""
    lines = []
    seqs = report.get("per_rank_seq", {})
    for node in sorted(seqs):
        lines.append(f"  rank {node}: collective seq {seqs[node]}")
    if report.get("lagging_rank"):
        lines.append(f"lagging rank: {report['lagging_rank']} "
                     f"(behind by {report['seq_skew']} collectives)")
    else:
        lines.append("no lagging rank (all ranks at the same seq)")
    fm = report.get("first_mismatch")
    if not report.get("desync"):
        lines.append("no collective desync detected in the compared window")
    elif fm and fm.get("seq") is not None:
        sigs = ", ".join(f"{n}={v}" for n, v in sorted(fm["signatures"]
                                                       .items()))
        lines.append(f"FIRST MISMATCHED COLLECTIVE: seq {fm['seq']} "
                     f"({sigs}); divergent rank(s): "
                     f"{', '.join(fm['divergent_ranks'])}")
    elif fm:
        lines.append(f"DESYNC: {fm['note']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# process-global instance + wiring
# ---------------------------------------------------------------------------

_default = CollectiveLedger()


def get_collective_ledger() -> CollectiveLedger:
    return _default


def attach_collective_ledger(ledger: Optional[CollectiveLedger]) -> None:
    """Point ``comms_logger`` at ``ledger`` (or detach with ``None``) —
    every call-site record then feeds the ledger regardless of whether
    the comms logger itself is enabled."""
    from ..comm.comm import comms_logger

    comms_logger.ledger = ledger


def configure_collective_ledger(enabled: bool = True,
                                max_entries: Optional[int] = None,
                                tail: Optional[int] = None,
                                exec_feed: Optional[bool] = None,
                                recorder: Any = None) -> CollectiveLedger:
    """Resolve config into the global ledger: enable it, hook it into the
    comms logger, and (when a flight recorder is given) register the
    snapshot as a bundle context provider so every future debug bundle
    carries this rank's ledger tail.  Idempotent."""
    led = _default.configure(enabled=enabled, max_entries=max_entries,
                             tail=tail, exec_feed=exec_feed)
    attach_collective_ledger(led if enabled else None)
    if recorder is not None and enabled:
        recorder.register_context("collective_ledger", led.snapshot)
    return led
