"""Metrics registry — counters, gauges, fixed-bucket histograms.

Two exporters, both text-based and dependency-free:

* **JSONL event log** — every ``emit_event`` appends one JSON object per
  line (``{"ts": ..., "kind": ..., ...payload}``) to ``events.jsonl``;
  the engine's per-step :class:`~.step_record.StepRecord` rides this as
  ``kind="step"`` so BENCH artifacts and post-hoc analysis read the same
  numbers the runtime logged.
* **Prometheus text exposition** — ``prometheus_text()`` renders the
  whole registry in the exposition format (``# TYPE``/``# HELP`` +
  samples; histograms as cumulative ``_bucket{le=...}``/``_sum``/
  ``_count``), writable to a file for node-exporter textfile collection
  or servable directly.

Everything is thread-safe (the swapper's pipeline worker and debug
callbacks bump counters off the main thread).
"""

from __future__ import annotations

import bisect
import json
import math
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Sanitize a slash-namespaced metric name ('swap/evictions') into a
    legal Prometheus metric name ('swap_evictions')."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def escape_help(text: str) -> str:
    """Exposition-format HELP escaping: backslash and newline (a raw
    newline in help text would truncate the comment line and leave the
    remainder as a malformed sample)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value: Any) -> str:
    """Exposition-format label-value escaping: backslash, double-quote,
    newline — the three characters that can break out of ``v="..."``."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_labels(labels: Dict[str, Any]) -> str:
    """Render ``{k="v",...}`` with escaped values ('' for no labels)."""
    if not labels:
        return ""
    inner = ",".join(f'{prom_name(str(k))}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


class Counter:
    """Monotonic counter."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(f"counter {self.name}: negative increment {v}")
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value

    def samples(self) -> List[tuple]:
        return [(prom_name(self.name), "", self._value)]


class Gauge:
    """Set-to-current-value metric."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, v: float) -> None:
        with self._lock:
            self._value += float(v)

    @property
    def value(self) -> float:
        return self._value

    def samples(self) -> List[tuple]:
        return [(prom_name(self.name), "", self._value)]


#: default buckets suit step/IO latencies in milliseconds
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0, 2500.0, 5000.0, 10000.0)


class Histogram:
    """Fixed-bucket histogram (Prometheus cumulative-bucket semantics)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"histogram {name}: need at least one bucket")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # +1 for the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def bucket_counts(self) -> Dict[str, int]:
        """Cumulative count per upper bound (the exposition shape)."""
        out: Dict[str, int] = {}
        cum = 0
        with self._lock:
            for ub, c in zip(self.buckets, self._counts):
                cum += c
                out[repr(ub) if ub != math.inf else "+Inf"] = cum
            out["+Inf"] = cum + self._counts[-1]
        return out

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def samples(self) -> List[tuple]:
        base = prom_name(self.name)
        out = []
        for ub, cum in self.bucket_counts().items():
            out.append((base + "_bucket", format_labels({"le": ub}), cum))
        out.append((base + "_sum", "", self._sum))
        out.append((base + "_count", "", self._count))
        return out


def _render_value(value) -> str:
    """Exposition-format sample value.  Non-finite floats are legal
    samples (``NaN``/``+Inf``/``-Inf``) — an fp16 overflow step records
    loss=nan / grad_norm=inf, and export must survive exactly those
    unstable runs it exists to observe."""
    if isinstance(value, float):
        if math.isnan(value):
            return "NaN"
        if math.isinf(value):
            return "+Inf" if value > 0 else "-Inf"
        if value == int(value) and abs(value) < 2 ** 53:
            return str(int(value))
    return str(value)


class JSONLExporter:
    """Append-only JSON-lines event log (one object per line)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(path, "a")

    def write(self, obj: Dict[str, Any]) -> None:
        line = json.dumps(obj, default=str)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass  # flush-on-close of a dead fd; nothing left to save


class MetricsRegistry:
    """Get-or-create registry of named metrics + the two exporters."""

    def __init__(self) -> None:
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.event_log: Optional[JSONLExporter] = None

    # -- get-or-create -----------------------------------------------------

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help=help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._metrics)

    def reset(self) -> None:
        with self._lock:
            self._metrics = {}

    # -- cross-process snapshot (telemetry/rollup.py) ----------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able value snapshot of every metric — the unit the
        cross-process rollup ships over the store.  Counters/gauges
        carry their value; histograms carry RAW per-bucket counts (not
        cumulative) plus sum/count, so N snapshots merge by plain
        elementwise addition.  Help text rides along so the merged
        Prometheus export can render it without sharing a registry."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "histograms": {}}
        for name, m in self.metrics().items():
            if isinstance(m, Counter):
                out["counters"][name] = {"value": m.value, "help": m.help}
            elif isinstance(m, Gauge):
                out["gauges"][name] = {"value": m.value, "help": m.help}
            elif isinstance(m, Histogram):
                with m._lock:
                    counts = list(m._counts)
                    hsum, hcount = m._sum, m._count
                out["histograms"][name] = {
                    "buckets": list(m.buckets), "counts": counts,
                    "sum": hsum, "count": hcount, "help": m.help}
        return out

    # -- JSONL -------------------------------------------------------------

    def attach_event_log(self, path: str) -> None:
        if self.event_log is not None:
            self.event_log.close()
        self.event_log = JSONLExporter(path)

    def emit_event(self, kind: str, payload: Dict[str, Any]) -> None:
        if self.event_log is None:
            return
        self.event_log.write({"ts": time.time(), "kind": kind, **payload})

    # -- Prometheus --------------------------------------------------------

    def prometheus_text(self) -> str:
        """The registry in Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        snapshot = self.metrics()  # index the snapshot: a concurrent
        for name in sorted(snapshot):  # reset() must not KeyError a flush
            m = snapshot[name]
            base = prom_name(name)
            if m.help:
                lines.append(f"# HELP {base} {escape_help(m.help)}")
            lines.append(f"# TYPE {base} {m.kind}")
            for sample_name, labels, value in m.samples():
                lines.append(f"{sample_name}{labels} {_render_value(value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def save_prometheus(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(self.prometheus_text())
        os.replace(tmp, path)  # atomic for textfile-collector consumers
        return path


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Tiny exposition-format parser (used by tests and bench sanity
    checks): returns ``{sample_name{labels}: value}``.  Raises ValueError
    on a malformed sample line, which is exactly what 'parses cleanly'
    means in the acceptance criteria."""
    out: Dict[str, float] = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        try:
            key, val = ln.rsplit(" ", 1)
            out[key] = float(val)
        except Exception as e:
            raise ValueError(f"bad exposition line {ln!r}: {e}")
        if not re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})?$", key):
            raise ValueError(f"bad sample name {key!r}")
    return out
