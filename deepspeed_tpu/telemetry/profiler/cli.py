"""``telemetry profile`` — one command, every rank.

``profile --steps N`` posts a capture command through the rendezvous
store, waits for every worker's device-lane publication, and writes the
merged clock-aligned ``cluster_trace.json`` + ``calibration_report.
json`` into the output archive.  ``profile report`` re-renders a saved
archive; ``profile factors`` prints (or clears) the persisted
per-device-kind calibration factors.
"""

from __future__ import annotations

import json
import os
from typing import Any

from ...utils.logging import logger


def _client(endpoint: str) -> Any:
    if not endpoint:
        raise SystemExit("profile: no store endpoint — pass --endpoint "
                         "or set DS_RDZV_ENDPOINT")
    from ...elasticity.rendezvous import RendezvousClient

    return RendezvousClient(endpoint)


def _render_report(report: dict, limit: int = 12) -> None:
    factors = report.get("factors") or {}
    for kind, f in sorted(factors.items()):
        pretty = ", ".join(f"{b}={v:.2f}" for b, v in sorted(f.items()))
        print(f"  factors[{kind}]: {pretty}")
    flagged = report.get("flagged_ops") or []
    if flagged:
        print(f"  ops off by >2x ({len(flagged)}): "
              + ", ".join(flagged[:limit])
              + (" ..." if len(flagged) > limit else ""))
    else:
        print("  no op off by >2x — the roofline holds")
    for node, rep in sorted((report.get("nodes") or {}).items()):
        print(f"  {node}: measured {rep.get('measured_step_ms')}ms/step "
              f"vs modeled {rep.get('modeled_step_ms')}ms "
              f"(ratio {rep.get('step_ratio')}, "
              f"site {rep.get('site')}, "
              f"device {rep.get('device_kind')})")


def cmd_profile(args: Any) -> int:
    sub = getattr(args, "profile_cmd", "capture")
    if sub == "capture":
        from .fleet import assemble_fleet_profile, expected_nodes
        from .orchestrator import post_capture_command

        client = _client(args.endpoint)
        nodes = ([n for n in args.nodes.split(",") if n]
                 if args.nodes else expected_nodes(client))
        mode = "duration" if args.duration_ms > 0 else "window"
        req = post_capture_command(client, steps=args.steps,
                                   lead=args.lead, mode=mode,
                                   duration_ms=max(args.duration_ms, 0.0))
        print(f"profile: posted capture #{req} "
              f"({mode} mode, steps={args.steps}) — waiting for "
              f"{nodes or 'any publisher'}")
        try:
            summary = assemble_fleet_profile(client, req, args.out,
                                             nodes=nodes or None,
                                             timeout_s=args.timeout)
        except TimeoutError as e:
            print(f"profile: {e}")
            return 2
        print(f"profile: merged timeline -> {summary['cluster_trace']}")
        print(f"profile: calibration     -> "
              f"{summary['calibration_report']}")
        lanes = summary.get("device_lanes") or {}
        for node in sorted(lanes):
            print(f"  {node}: {lanes[node]} device events")
        if summary["missing"]:
            print(f"profile: MISSING lanes from {summary['missing']}")
        with open(summary["calibration_report"]) as fh:
            _render_report(json.load(fh))
        return 0 if not summary["missing"] else 2
    if sub == "report":
        path = args.archive
        if os.path.isdir(path):
            path = os.path.join(path, "calibration_report.json")
        with open(path) as fh:
            report = json.load(fh)
        print(f"calibration report: {path}")
        _render_report(report)
        return 0
    if sub == "factors":
        from .calibration import get_calibration_store

        store = get_calibration_store(args.path or None)
        if args.clear:
            store.reset()
            store.save()
            print(f"factors cleared -> {store.path}")
            return 0
        doc = store.to_dict()
        print(json.dumps({"path": store.path, "factors": doc}, indent=1))
        return 0
    logger.error(f"unknown profile subcommand {sub!r}")
    return 2


def add_profile_parser(sub: Any) -> None:
    pr = sub.add_parser(
        "profile",
        help="fleet-synchronized profiler capture: arm jax.profiler on "
             "every rank for one step window, merge the device lanes, "
             "calibrate the roofline")
    psub = pr.add_subparsers(dest="profile_cmd", required=True)

    cp = psub.add_parser("capture",
                         help="post a capture command and merge the "
                              "fleet's device lanes")
    cp.add_argument("--endpoint",
                    default=os.environ.get("DS_RDZV_ENDPOINT"),
                    help="rendezvous store host:port "
                         "(default: $DS_RDZV_ENDPOINT)")
    cp.add_argument("--steps", type=int, default=4,
                    help="train steps in the capture window")
    cp.add_argument("--lead", type=int, default=3,
                    help="steps of arming lead (the window opens at "
                         "max(rank step)+lead)")
    cp.add_argument("--duration-ms", type=float, default=0.0,
                    help="capture wall-time instead of steps (the "
                         "serving fleet has no shared step counter)")
    cp.add_argument("--nodes", default="",
                    help="comma-separated node ids to wait for "
                         "(default: the sealed gang / registered "
                         "serving workers)")
    cp.add_argument("--out", default="fleet_profiles/latest",
                    help="archive dir for the merged timeline + report")
    cp.add_argument("--timeout", type=float, default=60.0)
    cp.set_defaults(fn=cmd_profile)

    rp = psub.add_parser("report",
                         help="re-render a saved calibration report")
    rp.add_argument("archive",
                    help="archive dir or calibration_report.json")
    rp.set_defaults(fn=cmd_profile)

    fa = psub.add_parser("factors",
                         help="print or clear the persisted calibration "
                              "factors")
    fa.add_argument("--path", default="",
                    help="factors file (default: $DS_CALIBRATION_PATH "
                         "or the user cache)")
    fa.add_argument("--clear", action="store_true")
    fa.set_defaults(fn=cmd_profile)
