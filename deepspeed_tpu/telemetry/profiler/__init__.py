"""Fleet-synchronized profiler capture (ISSUE 20).

One command arms ``jax.profiler`` on every rank for the same step-index
window; the measured device lanes come back through the rendezvous
store, merge into the clock-aligned cluster timeline, and calibrate the
anatomy roofline.  See :mod:`.orchestrator` for the store protocol,
:mod:`.census` for the per-op measured-duration table,
:mod:`.calibration` for the measured-vs-modeled join and the persisted
per-device-kind factors, and :mod:`.fleet` for the rank-0 merge.
"""

from .calibration import (CalibrationStore, MISMATCH_FACTOR,
                          apply_report_to_store, build_calibration_report,
                          calibration_scale, default_calibration_path,
                          get_calibration_store)
from .census import classify_op, normalize_op, op_census, trace_census
from .fleet import (assemble_fleet_profile, build_fleet_calibration,
                    expected_nodes, load_profiles, persist_profiles,
                    wait_for_publications)
from .orchestrator import (CMD_KEY, PUB_PREFIX, ProfilerPlane,
                           configure_profiler_plane, get_profiler_plane,
                           post_capture_command, pub_key,
                           reset_profiler_plane)

__all__ = [
    "CMD_KEY", "PUB_PREFIX", "MISMATCH_FACTOR",
    "CalibrationStore", "ProfilerPlane",
    "apply_report_to_store", "assemble_fleet_profile",
    "build_calibration_report", "build_fleet_calibration",
    "calibration_scale", "classify_op", "configure_profiler_plane",
    "default_calibration_path", "expected_nodes", "get_calibration_store",
    "get_profiler_plane", "load_profiles", "normalize_op", "op_census",
    "persist_profiles", "post_capture_command", "pub_key",
    "reset_profiler_plane", "trace_census", "wait_for_publications",
]
