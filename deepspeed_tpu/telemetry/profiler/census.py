"""Per-op measured-duration census over a profiler trace's device lanes.

``profiling/collective_trace.py`` parses device-lane events but its
aggregation (:func:`~...profiling.collective_trace.parse_trace`) keeps
collectives only.  The fleet profiler needs the WHOLE device timeline:
every op's measured duration, normalized across recompiles (XLA suffixes
op names with ``.<n>`` uniquifiers that change per program) and
classified into the same compute / collective buckets the anatomy
roofline models — that classification is what lets the calibration join
put ``measured_ms`` next to ``modeled_ms`` per roofline component.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from ...profiling.collective_trace import (COLLECTIVE_PATTERNS,
                                           parse_trace_events)

#: XLA uniquifier suffixes: "fusion.123", "all-reduce.7.remat" — strip
#: trailing ".<digits>" segments so the same op aggregates across
#: programs/recompiles
_SUFFIX_RE = re.compile(r"(\.\d+)+$")

#: ops that are host<->device plumbing, not modeled by the roofline
_HOST_PATTERNS = ("infeed", "outfeed", "transfer", "copy-start",
                  "copy-done", "host")


def normalize_op(name: str) -> str:
    """Canonical op name: uniquifier suffixes stripped, lowered."""
    return _SUFFIX_RE.sub("", str(name)).strip().lower()


def classify_op(name: str) -> str:
    """Roofline bucket of one device op: ``collective`` / ``host`` /
    ``compute`` (the roofline's compute and hbm components are not
    separable per-op from a trace — both land in ``compute``)."""
    low = normalize_op(name)
    if any(p in low for p in COLLECTIVE_PATTERNS):
        return "collective"
    if any(p in low for p in _HOST_PATTERNS):
        return "host"
    return "compute"


def op_census(events: List[Dict[str, Any]], steps: int = 1,
              dedupe_lanes: bool = True,
              top_k: Optional[int] = None) -> Dict[str, Any]:
    """Aggregate device-lane events into the per-op measured table.

    ``events`` are ``{ts_us, dur_us, name, lane}`` rows
    (:func:`parse_trace_events` with ``patterns=None``).  With
    ``dedupe_lanes`` only the first device lane counts — in a
    single-process multi-device mesh every shard's lane shows the same
    program, and summing them would count each op ``local_device_count``
    times (the same rationale as ``feed_exec_census``).

    Returns ``{"ops": {name: {count, total_us, mean_us, per_step_us,
    bucket}}, "steps", "lanes", "device_total_us", "window_us",
    "bucket_us": {compute, collective, host}}``.
    """
    steps = max(int(steps), 1)
    lanes = sorted({ev["lane"] for ev in events})
    rows = events
    if dedupe_lanes and lanes:
        first = events[0]["lane"]
        rows = [ev for ev in events if ev["lane"] == first]
    ops: Dict[str, Dict[str, float]] = {}
    bucket_us = {"compute": 0.0, "collective": 0.0, "host": 0.0}
    t_min, t_max = None, None
    for ev in rows:
        dur = float(ev.get("dur_us", 0.0))
        if dur <= 0.0:
            continue
        ts = float(ev.get("ts_us", 0.0))
        t_min = ts if t_min is None else min(t_min, ts)
        t_max = ts + dur if t_max is None else max(t_max, ts + dur)
        name = normalize_op(ev.get("name", "?")) or "?"
        row = ops.setdefault(name, {"count": 0.0, "total_us": 0.0,
                                    "bucket": classify_op(name)})
        row["count"] += 1
        row["total_us"] += dur
        bucket_us[row["bucket"]] += dur
    for name, row in ops.items():
        row["total_us"] = round(row["total_us"], 1)
        row["mean_us"] = round(row["total_us"] / max(row["count"], 1), 2)
        row["per_step_us"] = round(row["total_us"] / steps, 2)
    if top_k is not None and len(ops) > top_k:
        keep = sorted(ops.items(), key=lambda kv: -kv[1]["total_us"])
        dropped = keep[int(top_k):]
        ops = dict(keep[:int(top_k)])
        if dropped:
            # never silently truncate: the residue stays visible as one
            # explicit remainder row so totals still reconcile
            ops["(other)"] = {
                "count": sum(r["count"] for _, r in dropped),
                "total_us": round(sum(r["total_us"] for _, r in dropped), 1),
                "mean_us": 0.0,
                "per_step_us": round(
                    sum(r["total_us"] for _, r in dropped) / steps, 2),
                "bucket": "compute"}
    total = sum(r["total_us"] for r in ops.values())
    return {
        "ops": ops,
        "steps": steps,
        "lanes": lanes,
        "device_total_us": round(total, 1),
        "device_per_step_us": round(total / steps, 2),
        "window_us": (round(t_max - t_min, 1)
                      if t_min is not None else 0.0),
        "bucket_us": {k: round(v, 1) for k, v in bucket_us.items()},
        "bucket_per_step_us": {k: round(v / steps, 2)
                               for k, v in bucket_us.items()},
    }


def trace_census(trace_dir: str, steps: int = 1,
                 dedupe_lanes: bool = True,
                 top_k: Optional[int] = None) -> Dict[str, Any]:
    """Per-op census straight from a ``jax.profiler.trace`` output dir."""
    return op_census(parse_trace_events(trace_dir, patterns=None),
                     steps=steps, dedupe_lanes=dedupe_lanes, top_k=top_k)
