"""Store-coordinated fleet profiler capture (ISSUE 20 tentpole).

One command — ``telemetry profile --steps N`` or ``POST /debug/profile``
on the serving front door — bumps a counter in the rendezvous store;
every gang worker's publisher beat (and every serving worker's heartbeat
loop) notices, agrees on a *shared step-index window* through a
max-merge in the store, arms ``jax.profiler`` for exactly that window,
and publishes a compact device-lane document back.  Rank 0 (or the CLI)
merges the lanes into the clock-aligned ``cluster_trace.json`` timeline
next to the host spans and joins measured per-op durations against the
anatomy roofline (:mod:`.calibration`).

Store protocol (all under ``profiler/``):

=====================================  ==================================
``profiler/cmd``                       capture-request counter (operator
                                       bumps via :func:`post_capture_
                                       command`)
``profiler/cmd/<req>/spec``            the capture spec (steps, lead,
                                       mode, posted_at store-clock)
``profiler/cmd/<req>/start``           max-merged start step: every
                                       worker proposes ``local_step +
                                       lead``; the max wins, so the
                                       window opens after EVERY rank has
                                       seen the command (data-parallel
                                       ranks advance in lockstep)
``profiler/cmd/<req>/acks``            workers that proposed (progress /
                                       debugging surface)
``profiler/pub/<node>``                one worker's capture result:
                                       census + compact device events +
                                       store-clock anchor + calibration
=====================================  ==================================

Step windows arm from :meth:`ProfilerPlane.on_step` — a two-attribute
check when idle, called outside the jitted step, so a disabled (or
merely unarmed) plane changes neither the step's jaxpr nor its compile
cache.  Capture wall time is booked to the goodput ledger's
``profiler`` bucket.  Duty-cycle continuous mode self-arms a window of
``duty_cycle_pct`` percent of every ``duty_period_steps`` steps into the
same bounded ring of trace dirs — always-on capture with a bounded
overhead budget (``bench.py`` gates it as ``profiler_overhead_pct``).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ...utils.logging import debug_once, logger

CMD_KEY = "profiler/cmd"
PUB_PREFIX = "profiler/pub/"

#: a command older than this (store clock) is ignored — a worker joining
#: long after a capture must not replay it
STALE_CMD_S = 120.0

#: compact device events kept in a store publication (the full trace
#: stays in the worker's ring dir)
MAX_PUB_EVENTS = 1500

#: per-op census rows kept in a publication
PUB_CENSUS_TOP_K = 48


def _spec_key(req: int) -> str:
    return f"profiler/cmd/{int(req)}/spec"


def _start_key(req: int) -> str:
    return f"profiler/cmd/{int(req)}/start"


def _acks_key(req: int) -> str:
    return f"profiler/cmd/{int(req)}/acks"


def pub_key(node_id: str) -> str:
    return PUB_PREFIX + str(node_id)


def post_capture_command(client: Any, steps: int = 4, lead: int = 3,
                         mode: str = "window",
                         duration_ms: float = 250.0) -> int:
    """Operator side: post ONE capture command; returns the request id
    the publications will carry.

    ``mode="window"`` captures ``steps`` train steps starting at the
    max-merged start index; ``mode="duration"`` captures ``duration_ms``
    of wall time immediately (the serving fleet has no shared step
    counter — a decode burst is windowed by time, not index)."""
    if mode not in ("window", "duration"):
        raise ValueError(f"unknown capture mode {mode!r} "
                         "(window | duration)")
    req = int(client.add(CMD_KEY, 1))
    client.set(_spec_key(req), {
        "steps": max(int(steps), 1),
        "lead": max(int(lead), 1),
        "mode": mode,
        "duration_ms": float(duration_ms),
        "posted_at": float(client.now()),
    }, journal=True)
    return req


class ProfilerPlane:
    """Per-process capture service: polls the command channel from the
    publisher/heartbeat beat, arms ``jax.profiler`` for the agreed
    window from the engine's step hook, keeps a bounded ring of trace
    dirs, and publishes the measured census."""

    def __init__(self, node_id: str, out_dir: Optional[str] = None,
                 ring: int = 4, lead: int = 3,
                 duty_cycle_pct: float = 0.0,
                 duty_period_steps: int = 64,
                 site: Optional[str] = None,
                 goodput: Optional[Any] = None):
        self.node_id = str(node_id)
        self.out_dir = out_dir or os.path.join(
            tempfile.gettempdir(), f"ds_profiler_{self.node_id}")
        self.ring = max(int(ring), 1)
        self.lead = max(int(lead), 1)
        self.duty_cycle_pct = float(duty_cycle_pct)
        self.duty_period_steps = max(int(duty_period_steps), 2)
        #: anatomy site whose roofline entry the calibration joins
        #: against (the engine stamps its own; CLI captures pass theirs)
        self.site = site
        self._goodput = goodput
        self._lock = threading.Lock()
        self._step = 0
        self._last_req: Optional[int] = None
        #: the armed window: None when idle (the per-step fast path)
        self._armed: Optional[Dict[str, Any]] = None
        self._pending_pub: Optional[Dict[str, Any]] = None
        self._ring_dirs: List[str] = []
        self._captures = 0
        self.last_result: Optional[Dict[str, Any]] = None
        #: serving fold hook: called with the finished capture doc so a
        #: decode-burst's measured device time lands on the open request
        #: lifecycle records (serving/worker.py registers one)
        self._fold_hooks: List[Callable[[Dict[str, Any]], Any]] = []
        #: duty-cycle continuous mode: next self-armed window start
        self._duty_next_start: Optional[int] = None

    # -- wiring --------------------------------------------------------------

    def add_fold_hook(self, fn: Callable[[Dict[str, Any]], Any]) -> None:
        with self._lock:
            self._fold_hooks.append(fn)

    def register_bundle_context(self, recorder: Any = None) -> None:
        """``context.profiler`` in every flight-recorder bundle: the ring,
        the last capture summary, and whether a window is armed NOW."""
        if recorder is None:
            from ..flight_recorder import get_flight_recorder

            recorder = get_flight_recorder()
        if recorder is not None:
            recorder.register_context("profiler", self.context)

    def context(self) -> Dict[str, Any]:
        with self._lock:
            armed = dict(self._armed) if self._armed else None
            last = dict(self.last_result) if self.last_result else None
        if last:
            last.pop("events", None)  # bundles carry summaries, not lanes
            last.pop("census", None)
        return {"node": self.node_id, "step": self._step,
                "captures": self._captures, "armed": armed,
                "ring": list(self._ring_dirs),
                "duty_cycle_pct": self.duty_cycle_pct,
                "last_capture": last}

    # -- command channel (publisher/heartbeat beat) --------------------------

    def poll(self, client: Any) -> Optional[int]:
        """One command-channel beat.  Cheap when nothing changed: one
        ``get``.  Raises the client's ConnectionError family upward —
        the caller's degraded path (publisher tick) counts and retries.
        Returns the request id when a NEW command was adopted."""
        self._flush_pub(client)
        req = int(client.get(CMD_KEY) or 0)
        with self._lock:
            if self._last_req is None:
                # first beat: adopt the current counter as the baseline,
                # then look at the newest command below — a fresh command
                # posted moments before this process came up still runs,
                # anything stale is skipped by posted_at
                self._last_req = max(req - 1, 0)
            nothing_new = req <= self._last_req
        if nothing_new:
            self._refresh_start(client)
            return None
        spec = client.get(_spec_key(req))
        with self._lock:
            self._last_req = req
        if not isinstance(spec, dict):
            return None
        posted = float(spec.get("posted_at", 0.0))
        try:
            if posted and float(client.now()) - posted > STALE_CMD_S:
                debug_once("profiler/stale_cmd",
                           f"profiler: ignoring stale capture command "
                           f"#{req} (posted {posted:.0f})")
                return None
        except (OSError, ValueError):
            pass
        if spec.get("mode") == "duration":
            # time-windowed capture (serving fleet): run it right here on
            # the beat thread — the profiler traces the whole process, so
            # decode bursts on the serving threads land in the window
            self._capture_duration(client, req, spec)
            return req
        lead = int(spec.get("lead", self.lead))
        proposed = self._step + lead
        start = int(client.max(_start_key(req), proposed))
        client.add(_acks_key(req), 1)
        with self._lock:
            self._armed = {"req": req, "start": max(start, proposed),
                           "steps": max(int(spec.get("steps", 4)), 1),
                           "state": "pending", "source": "command"}
        logger.info(f"profiler[{self.node_id}]: armed capture #{req} for "
                    f"steps [{self._armed['start']}, "
                    f"{self._armed['start'] + self._armed['steps']})")
        return req

    def _refresh_start(self, client: Any) -> None:
        """While pending, other ranks may still be raising the max-merged
        start — track it so every rank opens at the same index."""
        with self._lock:
            a = self._armed
            if a is None or a["state"] != "pending" \
                    or a.get("source") != "command":
                return
            req = a["req"]
        start = client.get(_start_key(req))
        if isinstance(start, (int, float)):
            with self._lock:
                a = self._armed
                if a is not None and a["state"] == "pending" \
                        and a["req"] == req:
                    a["start"] = max(a["start"], int(start))

    def _flush_pub(self, client: Any) -> None:
        with self._lock:
            doc = self._pending_pub
        if doc is None:
            return
        client.set(pub_key(self.node_id), doc, journal=False)
        with self._lock:
            if self._pending_pub is doc:  # a newer capture may have won
                self._pending_pub = None

    # -- step hook (engine train loop) ---------------------------------------

    def on_step(self, step: int) -> None:
        """Called at the top of every train step, OUTSIDE the jitted
        program.  Idle cost: two attribute reads."""
        self._step = int(step)
        if self._armed is None:
            if self._duty_next_start is None:
                return
            self._maybe_duty_arm(step)
            if self._armed is None:
                return
        with self._lock:
            a = self._armed
            if a is None:
                return
            state, start = a["state"], a["start"]
        if state == "pending" and step >= start:
            self._begin_window(a)
        elif state == "active" and step >= a["start"] + a["steps"]:
            self._end_window(a)

    def enable_duty_cycle(self) -> None:
        """Arm the continuous mode: every ``duty_period_steps`` steps,
        capture ``duty_cycle_pct`` percent of them."""
        if self.duty_cycle_pct > 0.0:
            self._duty_next_start = self._step + self.duty_period_steps

    def _maybe_duty_arm(self, step: int) -> None:
        nxt = self._duty_next_start
        if nxt is None or step < nxt:
            return
        steps = max(int(round(self.duty_period_steps
                              * self.duty_cycle_pct / 100.0)), 1)
        with self._lock:
            if self._armed is None:
                self._armed = {"req": 0, "start": step, "steps": steps,
                               "state": "pending", "source": "duty"}
        self._duty_next_start = step + self.duty_period_steps

    # -- the window itself ---------------------------------------------------

    def _ring_slot(self, tag: str) -> str:
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"trace_{tag}")
        if os.path.isdir(path):  # re-captured tag: fresh slot
            shutil.rmtree(path, ignore_errors=True)
        with self._lock:
            self._ring_dirs.append(path)
            evict = (self._ring_dirs[:-self.ring]
                     if len(self._ring_dirs) > self.ring else [])
            self._ring_dirs = self._ring_dirs[-self.ring:]
        for old in evict:
            shutil.rmtree(old, ignore_errors=True)
        return path

    def _begin_window(self, a: Dict[str, Any]) -> None:
        from ...profiling.collective_trace import begin_shared_session

        tag = f"req{a['req']}_s{a['start']}" if a["req"] \
            else f"duty_s{a['start']}"
        tdir = self._ring_slot(tag)
        try:
            owned = begin_shared_session(tdir)
        except Exception as e:
            logger.warning(f"profiler[{self.node_id}]: trace start failed "
                           f"({e!r}); capture #{a['req']} dropped")
            with self._lock:
                self._armed = None
            return
        if owned is None:
            # someone else (an anatomy capture) holds the session — the
            # window re-arms one period later instead of fighting for it
            debug_once("profiler/session_busy",
                       f"profiler[{self.node_id}]: shared trace session "
                       f"busy; capture #{a['req']} skipped")
            with self._lock:
                self._armed = None
            return
        with self._lock:
            a["state"] = "active"
            a["trace_dir"] = owned
            a["t0_perf"] = time.perf_counter()
            a["t0_wall"] = time.time()

    def _end_window(self, a: Dict[str, Any]) -> None:
        from ...profiling.collective_trace import end_shared_session

        t_cap0 = time.perf_counter()
        try:
            end_shared_session()
        except Exception as e:
            logger.warning(f"profiler[{self.node_id}]: trace stop failed "
                           f"({e!r})")
            with self._lock:
                self._armed = None
            return
        window_s = t_cap0 - a["t0_perf"]
        doc = self._harvest(a, window_s)
        stop_s = time.perf_counter() - t_cap0
        # the window's steps already landed in productive/compile via
        # add_step; only the capture MACHINERY (trace stop + parse +
        # census) is profiler overhead — charging the steps themselves
        # would double-book them
        self._book_goodput(stop_s)
        with self._lock:
            self._armed = None
            self._captures += 1
            self.last_result = doc
            if a.get("source") == "command":
                self._pending_pub = doc
            hooks = list(self._fold_hooks)
        for fn in hooks:
            try:
                fn(doc)
            except Exception as e:
                debug_once("profiler/fold_hook",
                           f"profiler fold hook failed ({e!r})")
        logger.info(
            f"profiler[{self.node_id}]: capture "
            f"#{a['req']} done — {doc['census']['device_per_step_us']:.0f}"
            f"us device/step over {a['steps']} steps -> {a['trace_dir']}")

    def _book_goodput(self, seconds: float) -> None:
        led = self._goodput
        if led is None:
            from ..perf import get_goodput_ledger

            led = get_goodput_ledger()
        try:
            if led is not None:
                led.add("profiler", max(float(seconds), 0.0))
        except Exception as e:
            debug_once("profiler/goodput",
                       f"profiler goodput booking failed ({e!r})")

    def _harvest(self, a: Dict[str, Any], window_s: float
                 ) -> Dict[str, Any]:
        """Parse the trace, build the census + calibration, and shape
        the compact publication document."""
        from ...profiling.collective_trace import parse_trace_events
        from .calibration import (apply_report_to_store,
                                  build_calibration_report)
        from .census import op_census

        steps = int(a.get("steps", 1))
        events = parse_trace_events(a["trace_dir"], patterns=None)
        census = op_census(events, steps=steps, top_k=PUB_CENSUS_TOP_K)
        device_kind = self._device_kind()
        ledger_entry = self._ledger_entry()
        report = build_calibration_report(census, ledger_entry,
                                          device_kind=device_kind,
                                          node=self.node_id)
        try:
            report["factors"] = apply_report_to_store(report)
        except Exception as e:
            debug_once("profiler/calibration_store",
                       f"calibration persist failed ({e!r})")
            report["factors"] = {}
        compact = [
            {"ts_us": ev["ts_us"], "dur_us": ev["dur_us"],
             "name": ev["name"], "lane": ev["lane"]}
            for ev in sorted(events, key=lambda e: -e["dur_us"])
            [:MAX_PUB_EVENTS]]
        compact.sort(key=lambda e: e["ts_us"])
        clock = self._clock_anchor(a)
        return {
            "req": int(a["req"]),
            "node": self.node_id,
            "mode": a.get("mode", "window"),
            "start_step": int(a["start"]),
            "steps": steps,
            "window_s": round(window_s, 6),
            "trace_dir": a["trace_dir"],
            "device_kind": device_kind,
            "clock": clock,
            "census": census,
            "calibration": report,
            "events": compact,
            "events_truncated": max(len(events) - MAX_PUB_EVENTS, 0),
        }

    def _device_kind(self) -> str:
        try:
            import jax

            d = jax.devices()[0]
            return (getattr(d, "device_kind", "")
                    or getattr(d, "platform", "") or "unknown")
        except Exception:
            return "unknown"

    def _ledger_entry(self) -> Optional[Dict[str, Any]]:
        try:
            from ..anatomy.ledger import get_cost_ledger

            led = get_cost_ledger()
            if self.site:
                e = led.entry_for(self.site)
                if e:
                    return e
            top = led.top(1)
            return top[0] if top else None
        except Exception:
            return None

    def _clock_anchor(self, a: Dict[str, Any]) -> Dict[str, Any]:
        """The lane's place on the shared store clock: capture-start
        mapped through the clocksync offset (perf_counter -> store
        seconds), ``aligned`` false when no estimate is held."""
        from ..clocksync import get_clock_sync

        sync = get_clock_sync()
        off = sync.offset_s if sync.synced else None
        t0 = float(a.get("t0_perf", 0.0))
        return {
            "aligned": off is not None,
            "store_t0_s": (t0 + off) if off is not None else None,
            "wall_t0_s": float(a.get("t0_wall", 0.0)),
            "offset_s": off,
        }

    # -- duration mode (serving fleet) ---------------------------------------

    def _capture_duration(self, client: Any, req: int,
                          spec: Dict[str, Any]) -> None:
        from ...profiling.collective_trace import begin_shared_session

        ms = max(float(spec.get("duration_ms", 250.0)), 10.0)
        tdir = self._ring_slot(f"req{req}_t")
        try:
            owned = begin_shared_session(tdir)
        except Exception as e:
            logger.warning(f"profiler[{self.node_id}]: duration capture "
                           f"#{req} failed to start ({e!r})")
            return
        if owned is None:
            debug_once("profiler/session_busy",
                       f"profiler[{self.node_id}]: shared session busy; "
                       f"duration capture #{req} skipped")
            return
        a = {"req": req, "start": self._step, "steps": 1,
             "state": "active", "trace_dir": owned, "mode": "duration",
             "t0_perf": time.perf_counter(), "t0_wall": time.time()}
        time.sleep(ms / 1e3)  # the beat thread sleeps; serving threads run
        self._end_window(a)
        self._flush_pub(client)


_plane: Optional[ProfilerPlane] = None
_plane_lock = threading.Lock()


def get_profiler_plane() -> Optional[ProfilerPlane]:
    with _plane_lock:
        return _plane


def configure_profiler_plane(node_id: str, **kw: Any
                             ) -> ProfilerPlane:
    """Install the process-global plane (idempotent per node_id: a
    re-initialize with the same node reuses the instance so an armed
    window survives engine rebuilds)."""
    global _plane
    with _plane_lock:
        if _plane is None or _plane.node_id != str(node_id):
            _plane = ProfilerPlane(node_id, **kw)
        return _plane


def reset_profiler_plane() -> None:
    """Test isolation."""
    global _plane
    with _plane_lock:
        _plane = None
