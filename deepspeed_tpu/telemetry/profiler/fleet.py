"""Rank-0 / CLI side of a fleet capture: wait for every worker's
publication, persist the device lanes into an archive, merge them into
the clock-aligned ``cluster_trace.json`` (next to host bundle spans when
the archive has them), and write the fleet calibration report.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ...utils.logging import logger
from .orchestrator import PUB_PREFIX, pub_key

#: archive subdir the merged-trace builder scans for device lanes
PROFILES_DIR = "profiles"
CALIBRATION_REPORT = "calibration_report.json"
FLEET_PROFILE = "fleet_profile.json"


def expected_nodes(client: Any) -> List[str]:
    """The capture's answer set: the sealed gang when a round exists,
    else every serving/worker registration, else whoever has EVER
    published a profile."""
    from ..aggregator import sealed_members

    try:
        sealed = sealed_members(client)
    except Exception:
        sealed = []
    if sealed:
        return sealed
    srv = [k.rsplit("/", 1)[-1] for k in client.keys("serving/srv/")]
    if srv:
        return sorted(srv)
    return sorted(k[len(PUB_PREFIX):] for k in client.keys(PUB_PREFIX))


def wait_for_publications(client: Any, req: int,
                          nodes: Optional[List[str]] = None,
                          timeout_s: float = 60.0,
                          poll_s: float = 0.2) -> Dict[str, Dict[str, Any]]:
    """Block until every expected node's ``profiler/pub/<node>`` carries
    this request id (or the deadline passes — partial fleets are
    reported, not hidden: missing nodes simply aren't in the result)."""
    deadline = time.monotonic() + float(timeout_s)
    nodes = list(nodes) if nodes else None
    got: Dict[str, Dict[str, Any]] = {}
    while time.monotonic() < deadline:
        pending = (set(nodes) - set(got)) if nodes is not None else None
        keys = ([pub_key(n) for n in sorted(pending)]
                if pending is not None else client.keys(PUB_PREFIX))
        for k in keys:
            doc = client.get(k)
            if isinstance(doc, dict) and int(doc.get("req", -1)) >= int(req):
                got[str(doc.get("node") or k[len(PUB_PREFIX):])] = doc
        if nodes is not None and not (set(nodes) - set(got)):
            break
        if nodes is None and got:
            # no expected set: one settle poll after the first answer
            time.sleep(max(poll_s, 0.5))
            for k in client.keys(PUB_PREFIX):
                doc = client.get(k)
                if isinstance(doc, dict) \
                        and int(doc.get("req", -1)) >= int(req):
                    got[str(doc.get("node")
                            or k[len(PUB_PREFIX):])] = doc
            break
        time.sleep(poll_s)
    return got


def persist_profiles(archive: str, pubs: Dict[str, Dict[str, Any]]
                     ) -> List[str]:
    """Write each node's publication under ``<archive>/profiles/<node>/
    device_events.json`` — the layout ``build_cluster_trace`` merges."""
    written = []
    for node, doc in sorted(pubs.items()):
        pdir = os.path.join(archive, PROFILES_DIR, node)
        os.makedirs(pdir, exist_ok=True)
        path = os.path.join(pdir, "device_events.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, default=str)
        os.replace(tmp, path)
        written.append(path)
    return written


def load_profiles(archive: str) -> Dict[str, Dict[str, Any]]:
    """``{node: publication}`` back out of an archive's profiles tree."""
    pdir = os.path.join(archive, PROFILES_DIR)
    out: Dict[str, Dict[str, Any]] = {}
    if not os.path.isdir(pdir):
        return out
    for node in sorted(os.listdir(pdir)):
        path = os.path.join(pdir, node, "device_events.json")
        if not os.path.exists(path):
            continue
        try:
            with open(path) as fh:
                out[node] = json.load(fh)
        except (OSError, ValueError) as e:
            logger.warning(f"fleet profile: unreadable lane for {node} "
                           f"({e!r}); skipped")
    return out


def build_fleet_calibration(pubs: Dict[str, Dict[str, Any]]
                            ) -> Dict[str, Any]:
    """Merge per-node calibration reports into one fleet document: every
    node's rows, plus the fleet-level flagged-op union and the factor
    table per device kind."""
    nodes = {}
    flagged = set()
    factors: Dict[str, Dict[str, float]] = {}
    for node, doc in sorted(pubs.items()):
        rep = doc.get("calibration") or {}
        nodes[node] = rep
        flagged.update(rep.get("flagged") or [])
        kind = str(rep.get("device_kind") or "unknown")
        if rep.get("factors"):
            factors[kind] = {k: float(v)
                             for k, v in rep["factors"].items()}
    return {
        "nodes": nodes,
        "flagged_ops": sorted(flagged),
        "factors": factors,
        "mismatch_factor": 2.0,
    }


def assemble_fleet_profile(client: Any, req: int, out_dir: str,
                           nodes: Optional[List[str]] = None,
                           timeout_s: float = 60.0) -> Dict[str, Any]:
    """The whole rank-0 merge: wait for the fleet's publications, write
    the archive (device lanes + merged clock-aligned ``cluster_trace.
    json`` + ``calibration_report.json``), return the summary."""
    from ..aggregator import build_cluster_trace

    nodes = list(nodes) if nodes else expected_nodes(client)
    pubs = wait_for_publications(client, req, nodes or None,
                                 timeout_s=timeout_s)
    if not pubs:
        raise TimeoutError(
            f"fleet profile #{req}: no publications within {timeout_s}s "
            f"(expected {nodes or 'any'}) — are the workers' publisher "
            f"beats running against this store?")
    os.makedirs(out_dir, exist_ok=True)
    persist_profiles(out_dir, pubs)
    trace_doc = build_cluster_trace(out_dir)
    calib = build_fleet_calibration(pubs)
    with open(os.path.join(out_dir, CALIBRATION_REPORT), "w") as fh:
        json.dump(calib, fh, indent=1, default=str)
    missing = sorted(set(nodes or []) - set(pubs))
    summary = {
        "req": int(req),
        "archive": out_dir,
        "nodes": sorted(pubs),
        "missing": missing,
        "cluster_trace": (os.path.join(out_dir, "cluster_trace.json")
                          if trace_doc else None),
        "calibration_report": os.path.join(out_dir, CALIBRATION_REPORT),
        "flagged_ops": calib["flagged_ops"],
        "factors": calib["factors"],
        "device_lanes": {n: len(p.get("events") or [])
                         for n, p in pubs.items()},
    }
    with open(os.path.join(out_dir, FLEET_PROFILE), "w") as fh:
        json.dump(summary, fh, indent=1, default=str)
    if missing:
        logger.warning(f"fleet profile #{req}: missing lanes from "
                       f"{missing} — merged what answered")
    return summary
