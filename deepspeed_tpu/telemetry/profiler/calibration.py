"""Measured-vs-modeled calibration — the trace grounds the roofline.

The anatomy ledger's predictions (``telemetry/anatomy/ledger.py``) come
from the compiler cost model divided by spec-sheet peaks — analytic
twice over on backends without a cost model.  ROADMAP carries the debt
explicitly: every PR-12 crossover threshold and kernel speedup is a
measured-once constant awaiting re-verification.  This module closes the
loop: join a capture's per-op census (``measured_ms``) against the
ledger's per-site predictions (``modeled_ms``), flag every row where the
model is off by more than :data:`MISMATCH_FACTOR`, and persist per
device-kind calibration factors (EWMA, the same estimator the tuning
memory model uses) so

* subsequent :meth:`CostLedger.record` calls emit ``calibrated_us``
  grounded in measurement, and
* the tuning space's Pallas crossover thresholds
  (:func:`~...tuning.space.apply_calibration`) shift with the measured
  compute factor instead of the typed-in constant.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from ...utils.logging import logger

#: measured/modeled ratio beyond which (either way) a row is flagged
MISMATCH_FACTOR = 2.0

#: EWMA smoothing for factor updates (same order as the tuning memory
#: model's calibration: new captures dominate, history damps jitter)
EWMA_ALPHA = 0.5

#: factor clamp: a degenerate capture (empty lane, one op) must not swing
#: every subsequent prediction by orders of magnitude
FACTOR_MIN, FACTOR_MAX = 0.05, 20.0

#: roofline components a factor is kept for.  ``step`` scales the
#: whole-program prediction; ``compute``/``collective`` scale the
#: breakdown components the census can actually separate per-op.
FACTOR_BUCKETS = ("step", "compute", "collective")


def default_calibration_path() -> str:
    """Where factors persist across runs: ``DS_CALIBRATION_PATH`` env
    override (tests, multi-tenant hosts), else a dotfile next to the
    telemetry logs in the user cache."""
    env = os.environ.get("DS_CALIBRATION_PATH")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "deepspeed_tpu", "calibration.json")


class CalibrationStore:
    """Per-device-kind measured/modeled factors with EWMA updates.

    ``factors[device_kind][bucket] = {"factor", "samples"}``.  A factor
    of 1.0 means the analytic model matched measurement; >1 means the
    device is measured SLOWER than modeled (predictions scale up).
    Thread-safe; persistence is atomic-rename JSON.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_calibration_path()
        self._lock = threading.Lock()
        self._factors: Dict[str, Dict[str, Dict[str, float]]] = {}
        self._loaded = False

    # -- persistence -------------------------------------------------------

    def load(self) -> "CalibrationStore":
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
            with self._lock:
                self._factors = {
                    str(k): {str(b): dict(v) for b, v in d.items()
                             if isinstance(v, dict)}
                    for k, d in (doc.get("factors") or {}).items()
                    if isinstance(d, dict)}
        except FileNotFoundError:
            pass
        except (OSError, ValueError) as e:
            logger.warning(f"calibration: unreadable {self.path} ({e!r}); "
                           f"starting fresh")
        with self._lock:
            self._loaded = True
        return self

    def save(self) -> Optional[str]:
        with self._lock:
            doc = {"v": 1, "factors": {k: {b: dict(v)
                                           for b, v in d.items()}
                                       for k, d in self._factors.items()}}
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
            return self.path
        except OSError as e:
            logger.warning(f"calibration: could not persist {self.path} "
                           f"({e!r})")
            return None

    # -- factors -----------------------------------------------------------

    def _ensure_loaded(self) -> None:
        with self._lock:
            loaded = self._loaded
        if not loaded:
            self.load()

    def factor(self, device_kind: str, bucket: str = "step") -> float:
        self._ensure_loaded()
        with self._lock:
            row = self._factors.get(str(device_kind), {}).get(str(bucket))
            return float(row["factor"]) if row else 1.0

    def factors_for(self, device_kind: str) -> Dict[str, float]:
        self._ensure_loaded()
        with self._lock:
            return {b: float(v["factor"])
                    for b, v in self._factors.get(str(device_kind),
                                                  {}).items()}

    def update(self, device_kind: str, bucket: str, ratio: float) -> float:
        """Fold one measured/modeled ratio into the EWMA factor; returns
        the new factor."""
        if bucket not in FACTOR_BUCKETS:
            raise ValueError(f"unknown calibration bucket {bucket!r} "
                             f"(one of {FACTOR_BUCKETS})")
        ratio = min(max(float(ratio), FACTOR_MIN), FACTOR_MAX)
        self._ensure_loaded()
        with self._lock:
            dev = self._factors.setdefault(str(device_kind), {})
            row = dev.get(bucket)
            if row is None:
                dev[bucket] = {"factor": ratio, "samples": 1}
                return ratio
            f = (1.0 - EWMA_ALPHA) * float(row["factor"]) \
                + EWMA_ALPHA * ratio
            f = min(max(f, FACTOR_MIN), FACTOR_MAX)
            row["factor"] = f
            row["samples"] = int(row.get("samples", 0)) + 1
            return f

    def to_dict(self) -> Dict[str, Any]:
        self._ensure_loaded()
        with self._lock:
            return {k: {b: dict(v) for b, v in d.items()}
                    for k, d in self._factors.items()}

    def reset(self) -> None:
        with self._lock:
            self._factors = {}
            self._loaded = True


_store: Optional[CalibrationStore] = None
_store_lock = threading.Lock()


def get_calibration_store(path: Optional[str] = None) -> CalibrationStore:
    """The process-global store; a ``path`` argument re-homes it (CLI
    ``--calibration`` flag, test isolation)."""
    global _store
    with _store_lock:
        if _store is None or (path and _store.path != path):
            _store = CalibrationStore(path)
        return _store


def calibration_scale(device_kind: str, bucket: str = "step") -> float:
    """Cheap read for prediction paths — 1.0 until a capture taught us
    otherwise.  Never raises (a broken store file must not take down
    ``CostLedger.record``)."""
    try:
        return get_calibration_store().factor(device_kind, bucket)
    except Exception:
        return 1.0


# ---------------------------------------------------------------------------
# the measured-vs-modeled join
# ---------------------------------------------------------------------------

def build_calibration_report(census: Dict[str, Any],
                             ledger_entry: Optional[Dict[str, Any]],
                             device_kind: str = "",
                             node: str = "",
                             mismatch_factor: float = MISMATCH_FACTOR
                             ) -> Dict[str, Any]:
    """Join one capture's per-op census against the cost ledger's
    roofline prediction for the captured site.

    The roofline models three components (compute, hbm, comm); a trace
    separates collectives from everything else, so the join happens at
    that granularity: the ``collective`` bucket's measured time lands
    against the modeled ``comm`` component, everything else against
    ``max(compute, hbm)`` (the roofline's non-comm critical path).  Each
    op row carries its measured time plus the modeled time attributed to
    its bucket, so the report names every op whose bucket the model
    misses by more than ``mismatch_factor`` — per-op modeled time is the
    bucket model scaled by the op's measured share (the trace cannot
    re-derive the compiler cost model per op; the bucket ratio is the
    honest resolution).
    """
    steps = max(int(census.get("steps", 1)), 1)
    bucket_meas_ms = {
        "collective": census["bucket_per_step_us"]["collective"] / 1e3,
        "compute": (census["bucket_per_step_us"]["compute"]
                    + census["bucket_per_step_us"]["host"]) / 1e3,
    }
    measured_step_ms = census.get("device_per_step_us", 0.0) / 1e3
    rows: List[Dict[str, Any]] = []
    modeled_step_ms = None
    bucket_model_ms: Dict[str, float] = {}
    if ledger_entry:
        bd = ledger_entry.get("predicted_breakdown_us") or {}
        modeled_step_ms = float(ledger_entry.get("predicted_us", 0.0)) / 1e3
        bucket_model_ms = {
            "collective": float(bd.get("comm", 0.0)) / 1e3,
            "compute": max(float(bd.get("compute", 0.0)),
                           float(bd.get("hbm", 0.0))) / 1e3,
        }
    for name, op in sorted((census.get("ops") or {}).items(),
                           key=lambda kv: -kv[1]["total_us"]):
        bucket = op.get("bucket", "compute")
        join_bucket = "collective" if bucket == "collective" else "compute"
        meas_ms = float(op["per_step_us"]) / 1e3
        row: Dict[str, Any] = {
            "op": name, "bucket": bucket,
            "count": int(op["count"]),
            "measured_ms": round(meas_ms, 4),
            "measured_share": round(
                meas_ms / measured_step_ms, 4) if measured_step_ms else 0.0,
        }
        model_ms = bucket_model_ms.get(join_bucket)
        bucket_meas = bucket_meas_ms.get(join_bucket, 0.0)
        if model_ms is not None and model_ms > 0.0 and bucket_meas > 0.0:
            share = meas_ms / bucket_meas
            row["modeled_ms"] = round(model_ms * share, 4)
            ratio = bucket_meas / model_ms
            row["ratio"] = round(ratio, 3)
            row["off_by_2x"] = bool(ratio > mismatch_factor
                                    or ratio < 1.0 / mismatch_factor)
        else:
            row["modeled_ms"] = None
            row["ratio"] = None
            row["off_by_2x"] = False
        rows.append(row)
    report: Dict[str, Any] = {
        "node": node,
        "device_kind": device_kind,
        "site": (ledger_entry or {}).get("site"),
        "steps": steps,
        "measured_step_ms": round(measured_step_ms, 4),
        "modeled_step_ms": (round(modeled_step_ms, 4)
                            if modeled_step_ms is not None else None),
        "provenance": (ledger_entry or {}).get("provenance"),
        "buckets": {},
        "ops": rows,
        "flagged": [r["op"] for r in rows if r["off_by_2x"]],
    }
    for b in ("compute", "collective"):
        model = bucket_model_ms.get(b)
        meas = bucket_meas_ms.get(b, 0.0)
        ratio = (meas / model) if model else None
        report["buckets"][b] = {
            "measured_ms": round(meas, 4),
            "modeled_ms": round(model, 4) if model is not None else None,
            "ratio": round(ratio, 3) if ratio else None,
            "off_by_2x": bool(ratio and (ratio > mismatch_factor
                                         or ratio < 1.0 / mismatch_factor)),
        }
    if modeled_step_ms and measured_step_ms:
        report["step_ratio"] = round(measured_step_ms / modeled_step_ms, 3)
    else:
        report["step_ratio"] = None
    return report


def apply_report_to_store(report: Dict[str, Any],
                          store: Optional[CalibrationStore] = None,
                          save: bool = True) -> Dict[str, float]:
    """Fold one calibration report's ratios into the persistent factors;
    returns the updated ``{bucket: factor}`` view for the device kind."""
    store = store or get_calibration_store()
    kind = str(report.get("device_kind") or "unknown")
    if report.get("step_ratio"):
        store.update(kind, "step", float(report["step_ratio"]))
    for bucket, key in (("compute", "compute"),
                        ("collective", "collective")):
        row = (report.get("buckets") or {}).get(bucket) or {}
        if row.get("ratio"):
            store.update(kind, key, float(row["ratio"]))
    if save:
        store.save()
    return store.factors_for(kind)
