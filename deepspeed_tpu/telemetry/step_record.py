"""Per-optimizer-step telemetry record.

The engine assembles ONE of these each ``train_step`` (device-fenced
step wall time, throughput, loss/grad-norm/loss-scale, cumulative comm
bytes from ``comm.comms_logger``, JAX live-buffer/host memory stats) and
publishes it through the metrics registry + JSONL event log — so
``bench.py``, the autotuner, and any monitor backend all read the SAME
numbers the runtime measured, instead of re-deriving their own
(ISSUE 1: the round-5 headline numbers were unwitnessed precisely
because the measuring code lived outside the engine).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from .metrics import MetricsRegistry

#: step-time histogram buckets (ms) — spans CPU-test steps through
#: multi-second streamed Infinity steps
STEP_TIME_BUCKETS_MS = (5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                        1000.0, 2500.0, 5000.0, 15000.0, 60000.0)


@dataclasses.dataclass
class StepRecord:
    step: int
    step_time_ms: float          # device-fenced wall time of this step
    device_fenced: bool          # True when a real fence closed the timing
    samples_per_sec: float
    tokens_per_sec: float
    loss: float
    grad_norm: float
    lr: float
    loss_scale: float
    overflow: bool
    skipped_steps: int
    comm_bytes: int              # cumulative comms_logger bytes so far
    comm_ops: int                # cumulative comms_logger op count so far
    tflops: float = 0.0          # 0 when flops_per_step unknown
    mfu: float = 0.0             # 0 when peak unknown
    memory: Dict[str, float] = dataclasses.field(default_factory=dict)
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        extra = d.pop("extra")
        d.update(extra)
        return d


def publish_step_record(registry: MetricsRegistry, rec: StepRecord) -> None:
    """Write one StepRecord through the registry (gauges for the latest
    values, counters for totals, a histogram for step-time distribution)
    and append it to the JSONL event log as ``kind="step"``."""
    registry.counter("train/steps_total",
                     "optimizer steps taken (incl. overflow skips)").inc()
    if rec.overflow:
        registry.counter("train/overflow_steps_total",
                         "fp16 overflow-skipped steps").inc()
    if rec.device_fenced:
        # the histogram is documented as DEVICE time; async-mode records
        # carry dispatch time and must not pollute it
        registry.histogram(
            "train/step_time_ms", "device-fenced optimizer step time (ms)",
            buckets=STEP_TIME_BUCKETS_MS).observe(rec.step_time_ms)
    g = registry.gauge
    g("train/step", "last optimizer step index").set(rec.step)
    g("train/step_time_ms_last", "last step time (ms)").set(rec.step_time_ms)
    g("train/samples_per_sec", "last-step samples/sec").set(
        rec.samples_per_sec)
    g("train/tokens_per_sec", "last-step tokens/sec").set(rec.tokens_per_sec)
    g("train/loss", "last-step mean loss").set(rec.loss)
    g("train/grad_norm", "last-step global grad norm").set(rec.grad_norm)
    g("train/lr", "last-step learning rate").set(rec.lr)
    g("train/loss_scale", "current fp16 loss scale").set(rec.loss_scale)
    g("train/skipped_steps", "cumulative overflow skips").set(
        rec.skipped_steps)
    g("comm/bytes_total", "cumulative comms_logger bytes").set(rec.comm_bytes)
    g("comm/ops_total", "cumulative comms_logger op count").set(rec.comm_ops)
    if rec.tflops:
        g("train/tflops", "achieved model TFLOP/s").set(rec.tflops)
    if rec.mfu:
        g("train/mfu", "model FLOPs utilization").set(rec.mfu)
    for k, v in rec.memory.items():
        g(f"memory/{k}", "memory_status() field").set(v)
    registry.emit_event("step", rec.to_dict())


def collect_memory_stats(include_live_buffers: bool = False
                         ) -> Dict[str, float]:
    """Device HBM + host memory stats, best-effort.  The live-buffer
    count is opt-in: ``jax.live_arrays()`` enumerates EVERY live array
    (O(all buffers)) — too expensive to pay on each step, so the engine
    samples it every few steps instead."""
    from ..utils.memory import memory_status

    out = dict(memory_status())
    if include_live_buffers:
        try:
            import jax

            out["live_buffers"] = float(len(jax.live_arrays()))
        except Exception as e:  # introspection API drift across jax
            from ..utils.logging import debug_once

            debug_once("step_record/live_buffers",
                       f"live-buffer count unavailable ({e!r})")
    return out
