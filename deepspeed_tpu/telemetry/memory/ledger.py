"""Memory ledger — per-pool byte accounting for HBM and host memory.

The stack can see *time* end-to-end (spans, StepRecords, the goodput
account) and *collectives* (the ledger), but until this plane existed
*memory* — the entire point of the ZeRO/offload/Infinity lineage — was a
single print helper.  The :class:`MemoryLedger` is the missing account:

* **Registration hooks at the real allocation sites** feed per-pool byte
  totals: ZeRO sharder placement registers ``params``/``optimizer``,
  ``offload`` registers its host-side masters and moments, the Infinity
  swapper registers its staging planes, inference-v2 registers the KV
  pool, the resilience plane registers tier-0 snapshot buffers.
* **Cross-checks against the runtime** each sample: the tracked total is
  compared with ``device.memory_stats()['bytes_in_use']`` and an
  optional ``jax.live_arrays()`` census — the DRIFT between "what we
  think we allocated" and "what XLA actually holds" is itself a metric
  (``memory/ledger_drift_bytes``): steady growth there is a leak in
  something the ledger doesn't know about.
* **Per-step numbers** (``peak_hbm_bytes`` / ``host_rss_bytes`` /
  ``swap_io_bytes``) ride ``StepRecord.extra``; a rolling HBM
  high-water + headroom fraction rides the watchdog
  ``heartbeat_payload`` so rank 0 publishes
  ``elastic/cluster_hbm_{max,headroom_min}``.

Like every singleton in the telemetry stack the global ledger is cheap
when disabled (one attribute read) and explicit instances are testable.
All mutation happens under one lock: registration sites run on the main
thread, IO accounting runs on offload/swapper worker threads, and the
watchdog thread reads summaries on trip.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ...utils.logging import debug_once

#: the pool taxonomy — where training-run bytes live (README "Where the
#: memory goes" documents each).  Registration is open (any string is
#: accepted) but attribution quality is measured against THESE names.
POOLS = ("params", "grads", "optimizer", "activations", "kv_cache",
         "swap_staging", "snapshot", "collective_scratch", "other")

#: IO lanes for ``record_io`` — swap traffic between tiers
IO_KINDS = ("h2d", "d2h", "disk_read", "disk_write")

_uniq = itertools.count()


def unique_key(prefix: str) -> str:
    """A collision-free registration key for sites that allocate in a
    loop (e.g. the sharder's per-tree zero materialization)."""
    return f"{prefix}#{next(_uniq)}"


# ---------------------------------------------------------------------------
# device-liveness probe (bounded: a dead TPU tunnel hangs jax.devices()
# indefinitely — observed 180 s+ in BENCH_r05 — so every device call on a
# failure path goes through here)
# ---------------------------------------------------------------------------

_unresponsive_lock = threading.Lock()
_unresponsive_detail: Optional[str] = None


def mark_device_unresponsive(detail: str) -> None:
    """Process-global latch: once a bounded probe times out, every later
    device introspection call (memory_status, ledger samples, bundle
    context providers) skips the device instead of hanging the very
    failure path that is trying to report the problem."""
    global _unresponsive_detail
    with _unresponsive_lock:
        _unresponsive_detail = detail


def clear_device_unresponsive() -> None:
    global _unresponsive_detail
    with _unresponsive_lock:
        _unresponsive_detail = None


def device_unresponsive() -> Optional[str]:
    with _unresponsive_lock:
        return _unresponsive_detail


def _default_probe() -> Dict[str, Any]:
    import jax

    devs = jax.local_devices()
    stats = {}
    if devs:
        try:
            stats = devs[0].memory_stats() or {}
        except Exception as e:  # CPU / tunnel backends without the API
            stats = {"error": repr(e)}
    return {"device_count": len(devs), "memory_stats": bool(stats)}


def probe_device_liveness(timeout_s: float = 20.0,
                          probe_fn: Optional[Callable[[], Any]] = None
                          ) -> Dict[str, Any]:
    """Bounded-timeout device health check (thread + deadline):
    ``jax.devices()`` + ``memory_stats()`` run on a daemon thread, the
    caller waits at most ``timeout_s``.  On timeout the process-global
    unresponsive latch is set and ``{"alive": False, ...}`` returns —
    the caller gets a fail-fast verdict instead of the 180 s+ hang a
    dead TPU tunnel otherwise produces."""
    box: Dict[str, Any] = {}
    fn = probe_fn or _default_probe

    def run():
        try:
            box["result"] = fn()
        except Exception as e:
            box["error"] = repr(e)

    t0 = time.monotonic()
    t = threading.Thread(target=run, daemon=True,
                         name="ds-device-liveness-probe")
    t.start()
    t.join(timeout_s)
    elapsed = round(time.monotonic() - t0, 3)
    if "result" in box:
        return {"alive": True, "elapsed_s": elapsed, "detail": box["result"]}
    if "error" in box:
        # the runtime ANSWERED (with an error) — responsive but unhealthy
        return {"alive": False, "elapsed_s": elapsed, "detail": box["error"]}
    detail = (f"device probe timed out after {timeout_s:.1f}s "
              f"(jax.devices()/memory_stats() unresponsive — dead "
              f"accelerator tunnel?)")
    mark_device_unresponsive(detail)
    return {"alive": False, "elapsed_s": elapsed, "detail": detail,
            "timed_out": True}


# ---------------------------------------------------------------------------
# host / device sampling primitives
# ---------------------------------------------------------------------------

def host_memory_bytes() -> Dict[str, float]:
    """Host-side numbers from procfs (bytes)."""
    out: Dict[str, float] = {}
    try:
        with open("/proc/meminfo") as f:
            info = {line.split(":")[0]: line.split()[1] for line in f}
        total = int(info["MemTotal"]) * 1024
        avail = int(info["MemAvailable"]) * 1024
        out["host_used_bytes"] = float(total - avail)
        out["host_available_bytes"] = float(avail)
    except (OSError, KeyError, ValueError, IndexError):
        pass
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            rss_pages = int(f.read().split()[1])
        out["host_rss_bytes"] = float(rss_pages
                                      * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    return out


def tree_nbytes(tree: Any) -> int:
    """Total bytes of a pytree of arrays (device or numpy)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        n = getattr(leaf, "nbytes", None)
        if n is None:
            n = np.asarray(leaf).nbytes
        total += int(n)
    return total


class MemoryLedger:
    """Per-pool byte account with device/host cross-checks."""

    def __init__(self, enabled: bool = False, top_k: int = 10):
        self.enabled = bool(enabled)
        self.top_k = int(top_k)
        self._lock = threading.Lock()
        #: (pool, key) -> entry dict
        self._entries: Dict[tuple, Dict[str, Any]] = {}
        #: (shape, dtype-str) -> pool, for live-array provenance tagging
        self._shape_index: Dict[tuple, str] = {}
        self._io: Dict[str, float] = {k: 0.0 for k in IO_KINDS}
        self._peak_hbm_bytes = 0.0
        self._last_device: Dict[str, float] = {}
        self._last_host: Dict[str, float] = {}
        self._last_live_count: Optional[int] = None
        #: test seam — None uses jax.local_devices()[0].memory_stats()
        self._device_stats_fn: Optional[Callable[[], Dict]] = None

    # -- configuration -----------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  top_k: Optional[int] = None) -> "MemoryLedger":
        if enabled is not None:
            self.enabled = bool(enabled)
        if top_k is not None:
            self.top_k = int(top_k)
        return self

    def reset(self) -> None:
        """Test isolation: drop entries, IO totals, and the high-water."""
        with self._lock:
            self._entries = {}
            self._shape_index = {}
            self._io = {k: 0.0 for k in IO_KINDS}
            self._peak_hbm_bytes = 0.0
            self._last_device = {}
            self._last_host = {}
            self._last_live_count = None
            self._device_stats_fn = None

    # -- registration (the allocation-site hooks) --------------------------

    def register(self, pool: str, key: str, nbytes: int,
                 space: str = "hbm", tag: str = "",
                 transient: bool = False) -> None:
        """Account ``nbytes`` under ``pool`` at registration key ``key``
        (re-registering the same key replaces — the double-buffer /
        rebuild pattern).  ``space`` is ``"hbm"`` or ``"host"``;
        ``transient=True`` marks bytes that only exist inside a step
        (stage>=2 grads) — they stay in the breakdown but are excluded
        from the steady-state drift cross-check."""
        if not self.enabled:
            return
        with self._lock:
            self._entries[(str(pool), str(key))] = {
                "nbytes": int(nbytes), "space": str(space),
                "tag": str(tag), "transient": bool(transient),
                "ts": time.time()}

    def register_tree(self, pool: str, key: str, tree: Any,
                      space: str = "hbm", tag: str = "",
                      transient: bool = False) -> int:
        """Register a pytree of arrays; returns the byte total.  Leaf
        (shape, dtype) signatures are indexed so a later live-array
        census can attribute arrays back to this pool."""
        if not self.enabled:
            return 0
        import jax
        import numpy as np

        total = 0
        sigs = []
        for leaf in jax.tree.leaves(tree):
            n = getattr(leaf, "nbytes", None)
            if n is None:
                n = np.asarray(leaf).nbytes
            total += int(n)
            shape = tuple(getattr(leaf, "shape", ()) or ())
            dtype = str(getattr(leaf, "dtype", ""))
            if shape:
                sigs.append((shape, dtype))
        self.register(pool, key, total, space=space, tag=tag,
                      transient=transient)
        with self._lock:
            for sig in sigs:
                self._shape_index.setdefault(sig, str(pool))
        return total

    def release(self, pool: str, key: str) -> None:
        with self._lock:
            self._entries.pop((str(pool), str(key)), None)

    def record_io(self, kind: str, nbytes: float) -> None:
        """Swap traffic accounting (offload d2h grad pulls, h2d param
        pushes, Infinity NVMe reads/writes)."""
        if not self.enabled:
            return
        if kind not in self._io:
            raise ValueError(f"unknown io kind {kind!r} (one of {IO_KINDS})")
        with self._lock:
            self._io[kind] += float(nbytes)

    # -- accounting views --------------------------------------------------

    def pool_bytes(self, space: Optional[str] = None,
                   include_transient: bool = True) -> Dict[str, int]:
        out: Dict[str, int] = {}
        with self._lock:
            for (pool, _key), e in self._entries.items():
                if space is not None and e["space"] != space:
                    continue
                if not include_transient and e["transient"]:
                    continue
                out[pool] = out.get(pool, 0) + e["nbytes"]
        return out

    def tracked_bytes(self, space: Optional[str] = None,
                      include_transient: bool = False) -> int:
        return sum(self.pool_bytes(space=space,
                                   include_transient=include_transient)
                   .values())

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e, pool=pool, key=key)
                    for (pool, key), e in sorted(self._entries.items())]

    def io_totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._io)

    # -- runtime cross-checks ----------------------------------------------

    def device_stats(self) -> Dict[str, float]:
        """``memory_stats()`` of local device 0 (bytes), ``{}`` when the
        platform has none or the device is latched unresponsive."""
        if device_unresponsive() is not None:
            return {}
        fn = self._device_stats_fn
        try:
            if fn is not None:
                stats = fn() or {}
            else:
                import jax

                devs = jax.local_devices()
                stats = (devs[0].memory_stats() or {}) if devs else {}
        except Exception as e:  # CPU backends / tunnels without the API
            debug_once("memory/device_stats",
                       f"device memory_stats unavailable ({e!r})")
            return {}
        out = {}
        for k in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use",
                  "largest_free_block_bytes"):
            if k in stats:
                try:
                    out[k] = float(stats[k])
                except (TypeError, ValueError):
                    continue
        return out

    def live_array_census(self, top_k: Optional[int] = None
                          ) -> Dict[str, Any]:
        """``jax.live_arrays()`` sweep: count, total bytes, and the
        top-K arrays by nbytes with best-effort pool provenance (from
        the registered (shape, dtype) index).  O(all live buffers) —
        callers sample it, never run it per step."""
        from ...utils.jax_compat import live_arrays

        arrays = live_arrays()
        total = 0
        top: List[Dict[str, Any]] = []
        with self._lock:
            index = dict(self._shape_index)
        for a in arrays:
            try:
                n = int(a.nbytes)
                shape = tuple(a.shape)
                dtype = str(a.dtype)
            except Exception as e:  # deleted-buffer race mid-sweep
                debug_once("memory/census_leaf",
                           f"live-array introspection failed ({e!r})")
                continue
            total += n
            top.append({"nbytes": n, "shape": list(shape), "dtype": dtype,
                        "pool": index.get((shape, dtype), "untracked")})
        top.sort(key=lambda e: -e["nbytes"])
        k = self.top_k if top_k is None else int(top_k)
        census = {"count": len(arrays), "total_bytes": total,
                  "top": top[:k]}
        with self._lock:
            self._last_live_count = len(arrays)
        return census

    # -- sampling ----------------------------------------------------------

    def step_sample(self, live_census: bool = False) -> Dict[str, float]:
        """The per-step numbers that ride ``StepRecord.extra``.  Cheap:
        one ``memory_stats()`` call + procfs reads; the live-array
        census only when asked (the engine samples it every N steps)."""
        if not self.enabled:
            return {}
        dev = self.device_stats()
        host = host_memory_bytes()
        out: Dict[str, float] = {}
        in_use = dev.get("bytes_in_use", 0.0)
        limit = dev.get("bytes_limit", 0.0)
        peak = dev.get("peak_bytes_in_use", in_use)
        with self._lock:
            if peak > self._peak_hbm_bytes:
                self._peak_hbm_bytes = float(peak)
            rolled_peak = self._peak_hbm_bytes
            self._last_device = dict(dev)
            self._last_host = dict(host)
            io_total = sum(self._io.values())
        if dev:
            out["peak_hbm_bytes"] = float(rolled_peak)
            if limit > 0:
                out["hbm_frac"] = round(in_use / limit, 4)
                out["hbm_headroom_frac"] = round(1.0 - peak / limit, 4)
            tracked = self.tracked_bytes(space="hbm")
            if tracked:
                out["ledger_drift_bytes"] = float(in_use - tracked)
        if "host_rss_bytes" in host:
            out["host_rss_bytes"] = host["host_rss_bytes"]
        if io_total:
            out["swap_io_bytes"] = io_total
        if live_census:
            census = self.live_array_census()
            out["live_arrays"] = float(census["count"])
        self._publish(out)
        return out

    def _publish(self, sample: Dict[str, float]) -> None:
        try:
            from .. import get_telemetry

            tel = get_telemetry()
            if not tel.enabled:
                return
            for name, help_txt in (
                    ("peak_hbm_bytes", "rolling HBM high-water (bytes)"),
                    ("hbm_frac", "HBM bytes_in_use / bytes_limit"),
                    ("hbm_headroom_frac", "1 - peak HBM / limit"),
                    ("host_rss_bytes", "process resident set (bytes)"),
                    ("swap_io_bytes", "cumulative swap IO bytes"),
                    ("ledger_drift_bytes",
                     "device bytes_in_use minus ledger-tracked bytes")):
                if name in sample:
                    tel.set_gauge(f"memory/{name}", sample[name],
                                  help=help_txt)
            for pool, nbytes in self.pool_bytes().items():
                tel.set_gauge(f"memory/pool_{pool}_bytes", nbytes,
                              help=f"ledger-tracked bytes in pool {pool}")
        except Exception as e:  # metrics publish is best-effort
            debug_once("memory/publish",
                       f"memory gauge publish failed ({e!r})")

    def heartbeat_summary(self) -> Dict[str, float]:
        """Rides the watchdog ``heartbeat_payload``: rank 0 folds every
        host's values into ``elastic/cluster_hbm_{max,headroom_min}``.
        Reads ONLY the cached sample from the last ``step_sample`` — the
        heartbeat thread must never make a fresh (unbounded) device call:
        if the tunnel died before the first sample, hanging here would
        block the very heartbeat loop that reports the host alive."""
        with self._lock:
            dev = dict(self._last_device)
        out: Dict[str, float] = {}
        limit = dev.get("bytes_limit", 0.0)
        if limit > 0:
            with self._lock:
                peak = max(self._peak_hbm_bytes,
                           dev.get("peak_bytes_in_use", 0.0))
            out["hbm_frac"] = round(dev.get("bytes_in_use", 0.0) / limit, 4)
            out["hbm_headroom"] = round(1.0 - peak / limit, 4)
        return out

    # -- forensics ---------------------------------------------------------

    def snapshot(self, live_census: bool = False) -> Dict[str, Any]:
        """Bundle context payload: the full breakdown an operator reads
        post-mortem (and the cluster manifest compacts per host)."""
        pools_hbm = self.pool_bytes(space="hbm")
        pools_host = self.pool_bytes(space="host")
        tracked = sum(pools_hbm.values()) + sum(pools_host.values())
        named = sum(n for p, n in list(pools_hbm.items())
                    + list(pools_host.items()) if p in POOLS
                    and p != "other")
        dev = self.device_stats()
        host = host_memory_bytes()
        with self._lock:
            peak = self._peak_hbm_bytes
            live_count = self._last_live_count
        out: Dict[str, Any] = {
            "enabled": self.enabled,
            "pools_hbm_bytes": pools_hbm,
            "pools_host_bytes": pools_host,
            "tracked_bytes": tracked,
            "attributed_frac": round(named / tracked, 4) if tracked else 1.0,
            "io_bytes": self.io_totals(),
            "device": dev,
            "host": host,
            "peak_hbm_bytes": peak or dev.get("peak_bytes_in_use", 0.0),
            "entries": self.entries(),
        }
        if dev.get("bytes_limit"):
            out["hbm_frac"] = round(
                dev.get("bytes_in_use", 0.0) / dev["bytes_limit"], 4)
        if "host_rss_bytes" in host:
            out["host_rss_bytes"] = host["host_rss_bytes"]
        if dev.get("bytes_in_use") is not None and out["tracked_bytes"]:
            out["ledger_drift_bytes"] = (
                dev.get("bytes_in_use", 0.0)
                - self.tracked_bytes(space="hbm"))
        if live_count is not None:
            out["live_arrays"] = live_count
        if live_census:
            out["live_census"] = self.live_array_census()
        unresp = device_unresponsive()
        if unresp:
            out["device_unresponsive"] = unresp
        return out

    def status(self, cached: bool = False) -> Dict[str, float]:
        """The ``utils.memory.memory_status()`` surface (GB floats) —
        BOTH report the same numbers because both read this ledger.
        ``cached=True`` reuses the device/host readings the last
        :meth:`step_sample` already took — the engine assembles its
        StepRecord right after sampling, and must not pay the
        memory_stats RPC + procfs reads twice per step."""
        with self._lock:
            cached_host = dict(self._last_host)
            cached_dev = dict(self._last_device)
        host = (cached_host if cached and cached_host
                else host_memory_bytes())
        out: Dict[str, float] = {}
        GB = float(2 ** 30)
        if "host_used_bytes" in host:
            out["host_used_GB"] = host["host_used_bytes"] / GB
        if "host_available_bytes" in host:
            out["host_available_GB"] = host["host_available_bytes"] / GB
        if "host_rss_bytes" in host:
            out["process_rss_GB"] = host["host_rss_bytes"] / GB
        dev = cached_dev if cached else self.device_stats()
        if dev:
            out["device_in_use_GB"] = dev.get("bytes_in_use", 0.0) / GB
            out["device_limit_GB"] = dev.get("bytes_limit", 0.0) / GB
            out["device_peak_GB"] = dev.get("peak_bytes_in_use", 0.0) / GB
        if self.enabled:
            for pool, nbytes in sorted(self.pool_bytes().items()):
                out[f"pool_{pool}_GB"] = nbytes / GB
        return out


_default = MemoryLedger()


def get_memory_ledger() -> MemoryLedger:
    return _default


def configure_memory_ledger(enabled: bool = True,
                            top_k: Optional[int] = None,
                            recorder: Any = None) -> MemoryLedger:
    """Resolve config into the global ledger; with a flight recorder the
    breakdown lands in every debug bundle (context ``memory``) — which
    is how the cluster manifest learns per-host memory."""
    led = _default.configure(enabled=enabled, top_k=top_k)
    if recorder is not None and enabled:
        # census at DUMP time: live_arrays() is client-side metadata
        # (never touches the device), and bundles are not a hot path —
        # so every bundle's memory section supports `mem top`
        recorder.register_context(
            "memory", lambda: led.snapshot(live_census=True))
    return led
