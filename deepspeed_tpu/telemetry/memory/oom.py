"""OOM forensics — turn a raw ``RESOURCE_EXHAUSTED`` into an answer.

An XLA out-of-memory today dies with an allocator stack trace that names
a buffer size and nothing else.  This module is the catch path:

* :func:`is_oom_error` recognizes the XLA/jax OOM family
  (``RESOURCE_EXHAUSTED``, allocator "Out of memory", pjrt allocation
  failures) without importing backend-specific exception types.
* :func:`handle_oom` snapshots the memory ledger breakdown plus the
  top-K live arrays by nbytes (with pool provenance tags) into the
  flight-recorder bundle — ``memory.json`` next to the manifest — and
  builds an :class:`HBMExhaustedError` whose MESSAGE names the top
  pools, so the traceback an operator first sees already says where the
  bytes went.
* The engine wraps its step dispatch with this path; the flight
  recorder's excepthook calls :func:`augment_bundle_on_oom` so an OOM
  outside the engine (state placement, first compile) gets the same
  ``memory.json``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ...utils.logging import debug_once, logger
from .ledger import MemoryLedger, get_memory_ledger

#: substrings that mark the XLA/jax OOM family (matched against the
#: exception text and type name — backend exception classes moved
#: between jaxlib releases, so duck-typing beats isinstance here)
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
               "Out of memory", "out of memory",
               "Resource exhausted", "OOM when allocating",
               "Failed to allocate")

MEMORY_JSON = "memory.json"


class HBMExhaustedError(RuntimeError):
    """Device memory exhausted — raised with the ledger's verdict.

    ``top_pools`` is the [(pool, bytes), ...] breakdown (largest first),
    ``bundle_path`` the debug bundle carrying ``memory.json`` (None when
    the flight recorder is off), ``report`` the full forensics dict."""

    def __init__(self, message: str,
                 top_pools: Optional[List] = None,
                 bundle_path: Optional[str] = None,
                 report: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.top_pools = top_pools or []
        self.bundle_path = bundle_path
        self.report = report or {}
        #: the flight-recorder excepthook skips its own dump when the
        #: exception already carries a bundle (avoids a duplicate)
        self.ds_bundle_path = bundle_path


def is_oom_error(exc: BaseException) -> bool:
    if exc is None:
        return False
    if isinstance(exc, (HBMExhaustedError, MemoryError)):
        return True
    text = f"{type(exc).__name__}: {exc}"
    return any(marker in text for marker in OOM_MARKERS)


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def oom_report(ledger: Optional[MemoryLedger] = None,
               top_k: Optional[int] = None) -> Dict[str, Any]:
    """The ``memory.json`` payload: full ledger snapshot + live-array
    census (bounded: the census enumerates live buffers, which is safe —
    the allocation FAILED, so the device is responsive)."""
    led = ledger or get_memory_ledger()
    report = led.snapshot(live_census=True)
    report["kind"] = "oom_forensics"
    if top_k is not None and "live_census" in report:
        report["live_census"]["top"] = \
            report["live_census"]["top"][:int(top_k)]
    return report


def top_pools_of(report: Dict[str, Any], k: int = 3) -> List:
    """[(pool, bytes), ...] over BOTH spaces, largest first."""
    merged: Dict[str, float] = {}
    for space_key in ("pools_hbm_bytes", "pools_host_bytes"):
        for pool, nbytes in (report.get(space_key) or {}).items():
            merged[pool] = merged.get(pool, 0.0) + float(nbytes)
    ranked = sorted(merged.items(), key=lambda kv: -kv[1])
    return ranked[:k]


def write_memory_json(bundle_dir: str, report: Dict[str, Any]
                      ) -> Optional[str]:
    """Drop ``memory.json`` into an existing bundle dir (best-effort —
    a failed write must never mask the OOM itself)."""
    try:
        path = os.path.join(bundle_dir, MEMORY_JSON)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(report, fh, indent=2, default=str)
        os.replace(tmp, path)
        return path
    except OSError as e:
        logger.error(f"oom forensics: memory.json write failed: {e!r}")
        return None


def describe_oom(exc: BaseException, report: Dict[str, Any],
                 step: Optional[int] = None) -> str:
    """The operator-facing headline: names the top pools and the device
    numbers, so the raised traceback already answers 'where did the
    bytes go'."""
    pools = top_pools_of(report)
    parts = []
    if step is not None:
        parts.append(f"step {step}")
    dev = report.get("device") or {}
    if dev.get("bytes_limit"):
        parts.append(f"HBM {_fmt_bytes(dev.get('bytes_in_use', 0))} in use "
                     f"of {_fmt_bytes(dev['bytes_limit'])}")
    if pools:
        pool_txt = ", ".join(f"{p}={_fmt_bytes(n)}" for p, n in pools)
        parts.append(f"top pools: {pool_txt}")
    drift = report.get("ledger_drift_bytes")
    if drift is not None:
        parts.append(f"untracked drift {_fmt_bytes(drift)}")
    detail = "; ".join(parts) if parts else "no ledger data"
    top = pools[0][0] if pools else "unknown"
    return (f"device memory exhausted ({detail}) — biggest tracked pool "
            f"is '{top}'; see memory.json in the debug bundle for the "
            f"per-pool breakdown and top live arrays.  Original: "
            f"{type(exc).__name__}: {str(exc)[:300]}")


def handle_oom(exc: BaseException, recorder: Any = None,
               ledger: Optional[MemoryLedger] = None,
               step: Optional[int] = None) -> HBMExhaustedError:
    """Build the forensics bundle for an OOM and return the descriptive
    :class:`HBMExhaustedError` (the caller raises it ``from exc``)."""
    led = ledger or get_memory_ledger()
    try:
        report = oom_report(ledger=led)
    except Exception as e:  # forensics must never mask the OOM
        debug_once("memory/oom_report", f"oom report failed ({e!r})")
        report = {"kind": "oom_forensics", "error": repr(e)}
    bundle = None
    if recorder is not None:
        try:
            bundle = recorder.dump(
                f"HBM exhausted: {type(exc).__name__}: {str(exc)[:200]}",
                extra={"oom": True, "step": step,
                       "top_pools": top_pools_of(report)})
            write_memory_json(bundle, report)
        except Exception as e:
            logger.error(f"oom forensics: bundle dump failed: {e!r}")
    msg = describe_oom(exc, report, step=step)
    if bundle:
        msg += f"  [debug bundle: {bundle}]"
    try:
        from .. import get_telemetry

        get_telemetry().inc_counter(
            "memory/oom_events_total", help="recognized device OOMs")
    except Exception as e:
        debug_once("memory/oom_counter",
                   f"oom counter publish failed ({e!r})")
    return HBMExhaustedError(msg, top_pools=top_pools_of(report),
                             bundle_path=bundle, report=report)


def augment_bundle_on_oom(exc: BaseException,
                          bundle_dir: Optional[str]) -> bool:
    """Excepthook half of the catch path: when the crash that just
    dumped ``bundle_dir`` is an OOM, add ``memory.json`` so bundles from
    OUTSIDE the engine's own catch (placement, first compile, user
    code) carry the same forensics.  Returns True when written."""
    if not bundle_dir or not is_oom_error(exc):
        return False
    try:
        return write_memory_json(bundle_dir, oom_report()) is not None
    except Exception as e:
        debug_once("memory/oom_augment",
                   f"oom bundle augmentation failed ({e!r})")
        return False
