"""``python -m deepspeed_tpu.telemetry mem {show,top,diff}``.

The read side of the memory plane, for humans at 3am:

* ``mem show <bundle>`` — the pool breakdown, device/host numbers,
  drift, and IO totals of one bundle (``memory.json`` when present —
  an OOM bundle — else the manifest's ``context.memory`` /
  ``context.memory_status`` sections every bundle carries).
* ``mem top <bundle>``  — the top-K live arrays by nbytes with their
  pool provenance tags (OOM bundles and census-carrying snapshots).
* ``mem diff <a> <b>``  — two bundles of the SAME process over time:
  per-pool deltas, RSS delta, live-array-count delta, and a LEAK
  VERDICT — exit 3 when pool/RSS/live-count growth exceeds the
  thresholds (scriptable, same contract as ``desync``/``perf check``).

Every command works on plain directories — no store, no device.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Optional, Tuple

from .oom import MEMORY_JSON, _fmt_bytes

#: defaults for the diff leak verdict
LEAK_GROW_FRAC = 0.10
LEAK_GROW_BYTES_FLOOR = 16 << 20  # ignore sub-16MiB jitter
#: minimum live-array-count growth — a couple of scratch arrays alive
#: at dump time must not verdict-fail a scripted gate
LEAK_LIVE_COUNT_FLOOR = 64


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


def load_memory_section(bundle: str) -> Optional[Dict[str, Any]]:
    """Best memory payload available in a bundle dir: ``memory.json``
    (OOM forensics) wins; else the manifest's ``context.memory``; else a
    thin dict synthesized from ``context.memory_status``."""
    mj = os.path.join(bundle, MEMORY_JSON)
    if os.path.exists(mj):
        try:
            with open(mj) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            pass
    manifest = os.path.join(bundle, "bundle.json")
    if not os.path.exists(manifest):
        return None
    try:
        with open(manifest) as fh:
            ctx = (json.load(fh).get("context") or {})
    except (OSError, ValueError):
        return None
    mem = ctx.get("memory")
    if isinstance(mem, dict):
        return mem
    status = ctx.get("memory_status")
    if isinstance(status, dict):
        GB = float(2 ** 30)
        out: Dict[str, Any] = {"from_memory_status": True}
        if "process_rss_GB" in status:
            out["host_rss_bytes"] = float(status["process_rss_GB"]) * GB
        pools = {k[len("pool_"):-len("_GB")]: float(v) * GB
                 for k, v in status.items()
                 if k.startswith("pool_") and k.endswith("_GB")}
        if pools:
            # memory_status merges hbm+host per pool — the split is NOT
            # recoverable here, so these go under a space-unknown key
            # (mislabeling offload masters / snapshot buffers as HBM
            # would read as device pressure they are not)
            out["pools_bytes"] = pools
        if "device_in_use_GB" in status:
            out["device"] = {
                "bytes_in_use": float(status["device_in_use_GB"]) * GB,
                "bytes_limit": float(status.get("device_limit_GB", 0)) * GB,
                "peak_bytes_in_use":
                    float(status.get("device_peak_GB", 0)) * GB}
        return out
    return None


def _resolve(path: str) -> Optional[str]:
    from ..cli import _resolve_bundle

    return _resolve_bundle(path)


def _merged_pools(mem: Dict[str, Any]) -> Dict[str, Tuple[float, float]]:
    """pool -> (hbm_bytes, host_bytes).  Space-unknown pools (the
    memory_status fallback, which cannot recover the split) land in the
    first slot — ``diff`` sums both slots so its verdict is
    space-agnostic; ``show`` renders them without the hbm/host labels
    (see ``pools_bytes`` handling there)."""
    out: Dict[str, Tuple[float, float]] = {}

    def add(key: str, slot: int) -> None:
        for pool, n in (mem.get(key) or {}).items():
            cur = out.get(pool, (0.0, 0.0))
            out[pool] = ((cur[0] + float(n), cur[1]) if slot == 0
                         else (cur[0], cur[1] + float(n)))

    add("pools_hbm_bytes", 0)
    add("pools_host_bytes", 1)
    add("pools_bytes", 0)
    return out


# ---------------------------------------------------------------------------
# show
# ---------------------------------------------------------------------------

def cmd_mem_show(args: argparse.Namespace) -> int:
    bundle = _resolve(args.bundle)
    if bundle is None:
        return _fail(f"{args.bundle}: not a debug bundle")
    mem = load_memory_section(bundle)
    if mem is None:
        return _fail(f"{bundle}: no memory section (memory.json or "
                     f"manifest context.memory/memory_status)")
    print(f"bundle: {bundle}")
    dev = mem.get("device") or {}
    if dev.get("bytes_limit"):
        print(f"  HBM: {_fmt_bytes(dev.get('bytes_in_use', 0))} in use / "
              f"{_fmt_bytes(dev['bytes_limit'])} limit "
              f"(peak {_fmt_bytes(dev.get('peak_bytes_in_use', 0))})")
    if mem.get("host_rss_bytes") is not None:
        print(f"  host RSS: {_fmt_bytes(mem['host_rss_bytes'])}")
    pools = _merged_pools(mem)
    if pools:
        tracked = mem.get("tracked_bytes")
        attributed = mem.get("attributed_frac")
        space_unknown = bool(mem.get("pools_bytes"))
        head = ("  pools (hbm+host merged; from memory_status):"
                if space_unknown else "  pools (hbm / host):")
        if tracked is not None:
            head += f"  tracked {_fmt_bytes(tracked)}"
        if attributed is not None:
            head += f"  attributed {attributed:.0%}"
        print(head)
        for pool, (hbm, host) in sorted(pools.items(),
                                        key=lambda kv: -sum(kv[1])):
            if space_unknown:
                print(f"    {pool:<20} {_fmt_bytes(hbm + host):>10}")
            else:
                print(f"    {pool:<20} {_fmt_bytes(hbm):>10} / "
                      f"{_fmt_bytes(host):>10}")
    drift = mem.get("ledger_drift_bytes")
    if drift is not None:
        print(f"  ledger drift (device in-use − tracked): "
              f"{_fmt_bytes(drift)}")
    io = mem.get("io_bytes") or {}
    if any(io.values()):
        print("  swap IO: " + "  ".join(
            f"{k}={_fmt_bytes(v)}" for k, v in sorted(io.items()) if v))
    if mem.get("live_arrays") is not None:
        print(f"  live arrays: {int(mem['live_arrays'])}")
    if mem.get("device_unresponsive"):
        print(f"  DEVICE UNRESPONSIVE: {mem['device_unresponsive']}")
    return 0


# ---------------------------------------------------------------------------
# top
# ---------------------------------------------------------------------------

def cmd_mem_top(args: argparse.Namespace) -> int:
    bundle = _resolve(args.bundle)
    if bundle is None:
        return _fail(f"{args.bundle}: not a debug bundle")
    mem = load_memory_section(bundle)
    census = (mem or {}).get("live_census") or {}
    top = census.get("top") or []
    if not top:
        return _fail(f"{bundle}: no live-array census (only OOM bundles "
                     f"and census-carrying snapshots have one)")
    print(f"bundle: {bundle}")
    print(f"  live arrays: {census.get('count')} "
          f"({_fmt_bytes(census.get('total_bytes', 0))} total)")
    for e in top[:args.k]:
        shape = "x".join(str(d) for d in (e.get("shape") or [])) or "()"
        print(f"    {_fmt_bytes(e.get('nbytes', 0)):>10}  "
              f"{e.get('dtype', '?'):<10} {shape:<24} "
              f"pool={e.get('pool', 'untracked')}")
    return 0


# ---------------------------------------------------------------------------
# diff — the leak verdict
# ---------------------------------------------------------------------------

def diff_memory(a: Dict[str, Any], b: Dict[str, Any],
                grow_frac: float = LEAK_GROW_FRAC,
                grow_floor: int = LEAK_GROW_BYTES_FLOOR) -> Dict[str, Any]:
    """Compare OLD ``a`` against NEW ``b``; a growth beyond BOTH the
    fraction and the absolute floor on any pool / RSS / live-count is a
    leak finding."""
    findings = []
    pools_a, pools_b = _merged_pools(a), _merged_pools(b)
    pool_deltas: Dict[str, float] = {}
    for pool in sorted(set(pools_a) | set(pools_b)):
        pa = sum(pools_a.get(pool, (0.0, 0.0)))
        pb = sum(pools_b.get(pool, (0.0, 0.0)))
        delta = pb - pa
        pool_deltas[pool] = delta
        if delta > grow_floor and (pa <= 0 or delta / pa > grow_frac):
            findings.append(
                f"pool '{pool}' grew {_fmt_bytes(delta)} "
                f"({_fmt_bytes(pa)} -> {_fmt_bytes(pb)})")
    rss_a, rss_b = a.get("host_rss_bytes"), b.get("host_rss_bytes")
    rss_delta = None
    if rss_a is not None and rss_b is not None:
        rss_delta = float(rss_b) - float(rss_a)
        if rss_delta > grow_floor and rss_delta / max(float(rss_a), 1.0) \
                > grow_frac:
            findings.append(f"host RSS grew {_fmt_bytes(rss_delta)} "
                            f"({_fmt_bytes(rss_a)} -> {_fmt_bytes(rss_b)})")
    live_a, live_b = a.get("live_arrays"), b.get("live_arrays")
    live_delta = None
    if live_a is not None and live_b is not None:
        live_delta = int(live_b) - int(live_a)
        if (live_delta > LEAK_LIVE_COUNT_FLOOR
                and live_delta / max(int(live_a), 1) > grow_frac):
            findings.append(f"live-array count grew {int(live_a)} -> "
                            f"{int(live_b)}")
    return {"leak": bool(findings), "findings": findings,
            "pool_deltas": pool_deltas, "rss_delta": rss_delta,
            "live_delta": live_delta}


def cmd_mem_diff(args: argparse.Namespace) -> int:
    a, b = _resolve(args.a), _resolve(args.b)
    if a is None or b is None:
        return _fail("mem diff needs two debug bundle directories")
    ma, mb = load_memory_section(a), load_memory_section(b)
    if ma is None or mb is None:
        missing = a if ma is None else b
        return _fail(f"{missing}: no memory section")
    result = diff_memory(ma, mb, grow_frac=args.grow_frac,
                         grow_floor=args.grow_floor)
    print(f"A (old): {a}\nB (new): {b}")
    deltas = {p: d for p, d in result["pool_deltas"].items() if d}
    if deltas:
        print("pool deltas (B - A):")
        for pool, d in sorted(deltas.items(), key=lambda kv: -abs(kv[1])):
            print(f"  {pool:<20} {'+' if d > 0 else ''}{_fmt_bytes(d)}")
    if result["rss_delta"] is not None:
        d = result["rss_delta"]
        print(f"host RSS delta: {'+' if d > 0 else ''}{_fmt_bytes(d)}")
    if result["live_delta"] is not None:
        print(f"live-array delta: {result['live_delta']:+d}")
    if result["leak"]:
        print("LEAK VERDICT: "
              + "; ".join(result["findings"]))
        return 3
    print("no leak detected (growth within "
          f"{args.grow_frac:.0%} / {_fmt_bytes(args.grow_floor)})")
    return 0


# ---------------------------------------------------------------------------
# parser wiring (called from telemetry/cli.py build_parser)
# ---------------------------------------------------------------------------

def add_mem_parser(sub: Any) -> None:
    m = sub.add_parser("mem", help="memory ledger forensics: show/top/"
                                   "diff bundle memory sections "
                                   "(diff exits 3 on a leak verdict)")
    msub = m.add_subparsers(dest="mem_cmd", required=True)
    ms = msub.add_parser("show", help="one bundle's pool breakdown")
    ms.add_argument("bundle")
    ms.set_defaults(fn=cmd_mem_show)
    mt = msub.add_parser("top", help="top live arrays by nbytes")
    mt.add_argument("bundle")
    mt.add_argument("-k", type=int, default=10)
    mt.set_defaults(fn=cmd_mem_top)
    md = msub.add_parser("diff", help="diff two bundles' ledgers; "
                                      "exit 3 on leak verdict")
    md.add_argument("a", help="older bundle")
    md.add_argument("b", help="newer bundle")
    md.add_argument("--grow-frac", type=float, default=LEAK_GROW_FRAC,
                    help="relative growth that constitutes a leak "
                         f"(default {LEAK_GROW_FRAC})")
    md.add_argument("--grow-floor", type=int,
                    default=LEAK_GROW_BYTES_FLOOR,
                    help="absolute growth floor in bytes (default 16MiB)")
    md.set_defaults(fn=cmd_mem_diff)
