"""Memory observability plane (ISSUE 7).

Three cooperating pieces that make *memory* — the entire point of the
ZeRO/offload/Infinity lineage — a first-class observable, symmetric to
the perf plane:

* :mod:`.ledger` — the :class:`MemoryLedger`: per-pool byte accounting
  (params, grads, optimizer shards, activations, KV cache, swap
  staging, snapshot buffers, collective scratch) fed by registration
  hooks at the real allocation sites, cross-checked each sample against
  ``device.memory_stats()`` and a ``jax.live_arrays()`` census; plus
  the bounded device-liveness probe a dead TPU tunnel can't hang.
* :mod:`.oom` — OOM forensics: recognize ``RESOURCE_EXHAUSTED``, write
  ``memory.json`` (pool breakdown + top-K live arrays with provenance)
  into the flight-recorder bundle, raise a descriptive
  :class:`HBMExhaustedError` naming the top pools.
* :mod:`.cli` — ``python -m deepspeed_tpu.telemetry mem {show,top,diff}``
  (diff exits 3 on a leak verdict).
"""

from .ledger import (IO_KINDS, POOLS, MemoryLedger, clear_device_unresponsive,
                     configure_memory_ledger, device_unresponsive,
                     get_memory_ledger, host_memory_bytes,
                     mark_device_unresponsive, probe_device_liveness,
                     tree_nbytes, unique_key)
from .oom import (MEMORY_JSON, HBMExhaustedError, augment_bundle_on_oom,
                  handle_oom, is_oom_error, oom_report, top_pools_of,
                  write_memory_json)

__all__ = [
    "MemoryLedger", "get_memory_ledger", "configure_memory_ledger",
    "POOLS", "IO_KINDS", "tree_nbytes", "unique_key", "host_memory_bytes",
    "probe_device_liveness", "mark_device_unresponsive",
    "clear_device_unresponsive", "device_unresponsive",
    "HBMExhaustedError", "is_oom_error", "handle_oom", "oom_report",
    "top_pools_of", "write_memory_json", "augment_bundle_on_oom",
    "MEMORY_JSON",
]
