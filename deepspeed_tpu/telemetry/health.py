"""Training-health anomaly detection over the engine's StepRecords.

Streaming detectors — O(window) state, no history files — that turn the
per-step record stream into structured :class:`HealthEvent`\\ s:

* ``nan_loss``               — NaN/Inf loss (critical; the run is dead)
* ``loss_spike``             — z-score vs a rolling loss window
* ``grad_norm_explosion``    — non-finite, or ratio vs rolling median
* ``loss_scale_collapse``    — fp16 scale at the floor or in free-fall
* ``throughput_regression``  — tokens/sec vs rolling median (a silent
  straggler/thermal/backpressure signal the loss can't show)
* ``recompile_storm``        — too many recompile events within the
  window (a shape/dtype/static leak is re-tracing programs that should
  be cached; every one stalls the step loop for a compile)
* ``memory_pressure``        — HBM used fraction above threshold for N
  consecutive steps (the headroom signal autotuning and operators need
  BEFORE the OOM, fed by the memory ledger's per-step samples)
* ``host_memory_leak``       — monotonic host-RSS / live-array-count
  growth vs the rolling median (a leak in host staging, snapshot
  buffers, or un-freed jax arrays; quiet on flat or sawtooth usage)
* ``control_plane_degraded`` — a rendezvous-store client exhausted its
  retry budget (store killed / partitioned): heartbeats and replica
  publications are buffering, training continues blind — one event per
  outage streak, cleared on reconnect
* ``underflow_creep``         — worst probe underflow fraction above
  threshold for N consecutive sampled numerics captures (the loss scale
  should be bumped before the gradients silently flush to zero)
* ``layer_grad_explosion``    — ONE layer's grad norm is many times the
  median layer's (the per-layer [L] norm vector from the numerics
  plane); the event NAMES the layer index
* ``router_collapse``         — MoE gating entropy at/below its floor
  for N consecutive captures: the router is funneling every token to
  the same expert(s)

Compile-dominated steps (``extra["compile_ms"]`` at or above
``compile_dominated_frac`` of the step time — the CompileTracker's
per-step attribution) are EXCLUDED from the throughput window: a
first-step or rebucketing compile is expected cost, and letting it into
the rolling median would trip a false ``throughput_regression``.

Events are published everywhere an operator could be looking: counters +
a last-event gauge in the metrics registry, a ``kind="health"`` JSONL
event, the flight recorder's health ring (so the last anomalies are in
every debug bundle), and — via ``MonitorMaster.write_health_events`` —
the TensorBoard/W&B/CSV backends.

Detectors only read **device-fenced** records: the async-recording path
(``telemetry.device_fence: false``) carries NaN metric fields BY DESIGN
(pulling the loss would block), and must not fire ``nan_loss``.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Any, Dict, List, Optional

from .step_record import StepRecord

SEV_WARNING = "warning"
SEV_CRITICAL = "critical"


@dataclasses.dataclass
class HealthEvent:
    kind: str
    severity: str
    step: int
    message: str
    value: float      # the observed statistic (z-score, ratio, scale...)
    threshold: float  # the limit it crossed
    ts: float = dataclasses.field(default_factory=time.time)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class HealthMonitor:
    """Feed :meth:`observe` every StepRecord; get events back (also
    published through the registry/recorder/monitor handed in)."""

    def __init__(self, window: int = 32, min_points: int = 8,
                 loss_spike_zscore: float = 6.0,
                 grad_norm_ratio: float = 10.0,
                 loss_scale_floor: float = 1.0,
                 consecutive_scale_drops: int = 3,
                 throughput_frac: float = 0.5,
                 compile_dominated_frac: float = 0.5,
                 recompile_storm_threshold: int = 3,
                 memory_pressure_frac: float = 0.92,
                 memory_pressure_steps: int = 8,
                 host_leak_window: int = 16,
                 host_leak_frac: float = 0.05,
                 control_plane: bool = True,
                 numerics_underflow_frac: float = 0.05,
                 numerics_underflow_steps: int = 3,
                 numerics_layer_grad_ratio: float = 20.0,
                 numerics_layer_grad_floor: float = 1e-8,
                 numerics_entropy_floor: float = 0.30,
                 numerics_entropy_steps: int = 3,
                 registry: Optional[Any] = None,
                 recorder: Optional[Any] = None):
        self.min_points = max(2, int(min_points))
        self.loss_spike_zscore = float(loss_spike_zscore)
        self.grad_norm_ratio = float(grad_norm_ratio)
        self.loss_scale_floor = float(loss_scale_floor)
        self.consecutive_scale_drops = int(consecutive_scale_drops)
        self.throughput_frac = float(throughput_frac)
        #: a step whose compile_ms is at least this fraction of its step
        #: time is compile-dominated: progress, but not throughput signal
        self.compile_dominated_frac = float(compile_dominated_frac)
        #: RECOMPILE events (not first compiles) within the window that
        #: constitute a storm; <= 0 disables the rule
        self.recompile_storm_threshold = int(recompile_storm_threshold)
        #: HBM used fraction at or above which a step counts toward the
        #: memory_pressure streak; <= 0 disables the rule
        self.memory_pressure_frac = float(memory_pressure_frac)
        self.memory_pressure_steps = max(1, int(memory_pressure_steps))
        #: consecutive-growth window for the host-leak detector; the
        #: rule needs EVERY pair in the window to grow (flat stays
        #: quiet) AND the newest sample to clear the rolling median by
        #: ``host_leak_frac``; window < 2 disables the rule
        self.host_leak_window = int(host_leak_window)
        self.host_leak_frac = float(host_leak_frac)
        #: alert when a rendezvous-store client is in degraded mode
        #: (one event per outage streak, re-armed on reconnect)
        self.control_plane = bool(control_plane)
        self._cp_alerted = False
        #: numerics-plane rules (read StepRecord.extra["numerics"], the
        #: sampled-capture summary); <= 0 thresholds disable each rule
        self.numerics_underflow_frac = float(numerics_underflow_frac)
        self.numerics_underflow_steps = max(1, int(numerics_underflow_steps))
        self.numerics_layer_grad_ratio = float(numerics_layer_grad_ratio)
        self.numerics_layer_grad_floor = float(numerics_layer_grad_floor)
        self.numerics_entropy_floor = float(numerics_entropy_floor)
        self.numerics_entropy_steps = max(1, int(numerics_entropy_steps))
        self._underflow_streak = 0
        self._entropy_streak = 0
        self.registry = registry
        self.recorder = recorder
        w = max(int(window), self.min_points)
        self._losses: "collections.deque[float]" = collections.deque(maxlen=w)
        self._grad_norms: "collections.deque[float]" = collections.deque(
            maxlen=w)
        self._tps: "collections.deque[float]" = collections.deque(maxlen=w)
        #: per-step recompile counts over the window (storm detector)
        self._recompiles: "collections.deque[int]" = collections.deque(
            maxlen=w)
        lw = max(self.host_leak_window, 2)
        #: host-RSS and live-array-count series (leak detector)
        self._rss: "collections.deque[float]" = collections.deque(maxlen=lw)
        self._live: "collections.deque[float]" = collections.deque(maxlen=lw)
        self._pressure_streak = 0
        self._prev_scale: Optional[float] = None
        self._scale_drops = 0
        self._scale_collapsed = False  # fire the floor crossing once
        #: consecutive anomalous samples per windowed detector — once a
        #: streak reaches min_points the "spike" is a LEVEL SHIFT and the
        #: samples start entering the window, so the baseline re-bases
        #: instead of alerting on every step forever
        self._loss_anoms = 0
        self._gn_anoms = 0
        self.events_total = 0

    def reset_windows(self) -> None:
        """Drop the rolling baselines (losses / grad norms / throughput /
        scale streaks).  The resilience policy calls this after a
        rollback: the pre-rollback window saw the anomaly that triggered
        it, and replayed steps must be judged against a fresh baseline,
        not compared with the poisoned history."""
        self._losses.clear()
        self._grad_norms.clear()
        self._tps.clear()
        self._recompiles.clear()
        self._rss.clear()
        self._live.clear()
        self._pressure_streak = 0
        self._prev_scale = None
        self._scale_drops = 0
        self._scale_collapsed = False
        self._loss_anoms = 0
        self._gn_anoms = 0
        self._underflow_streak = 0
        self._entropy_streak = 0

    # -- detectors ---------------------------------------------------------

    def _check_loss(self, rec: StepRecord, out: List[HealthEvent]) -> None:
        loss = float(rec.loss)
        if not math.isfinite(loss):
            out.append(HealthEvent(
                "nan_loss", SEV_CRITICAL, rec.step,
                f"step {rec.step}: non-finite loss {loss}", loss, 0.0))
            return  # a NaN must never enter the rolling window
        if len(self._losses) >= self.min_points:
            mean = sum(self._losses) / len(self._losses)
            var = sum((x - mean) ** 2
                      for x in self._losses) / len(self._losses)
            # relative std floor: a near-constant loss window must not
            # turn fp jitter into an infinite z-score
            std = max(math.sqrt(var), 1e-3 * max(abs(mean), 1e-6))
            z = (loss - mean) / std
            if z >= self.loss_spike_zscore:
                out.append(HealthEvent(
                    "loss_spike", SEV_WARNING, rec.step,
                    f"step {rec.step}: loss {loss:.4g} is {z:.1f} sigma "
                    f"above the rolling mean {mean:.4g}",
                    z, self.loss_spike_zscore))
                self._loss_anoms += 1
                if self._loss_anoms < self.min_points:
                    # keep the baseline clean of a TRANSIENT spike; a
                    # sustained streak falls through and re-bases
                    return
            else:
                self._loss_anoms = 0
        self._losses.append(loss)

    def _check_grad_norm(self, rec: StepRecord,
                         out: List[HealthEvent]) -> None:
        gn = float(rec.grad_norm)
        if not math.isfinite(gn):
            out.append(HealthEvent(
                "grad_norm_explosion", SEV_CRITICAL, rec.step,
                f"step {rec.step}: non-finite grad norm {gn}", gn, 0.0))
            return
        if len(self._grad_norms) >= self.min_points:
            med = max(_median(list(self._grad_norms)), 1e-12)
            ratio = gn / med
            if ratio >= self.grad_norm_ratio:
                out.append(HealthEvent(
                    "grad_norm_explosion", SEV_WARNING, rec.step,
                    f"step {rec.step}: grad norm {gn:.4g} is {ratio:.1f}x "
                    f"the rolling median {med:.4g}",
                    ratio, self.grad_norm_ratio))
                self._gn_anoms += 1
                if self._gn_anoms < self.min_points:
                    return  # transient; a sustained streak re-bases
            else:
                self._gn_anoms = 0
        self._grad_norms.append(gn)

    def _check_loss_scale(self, rec: StepRecord,
                          out: List[HealthEvent]) -> None:
        scale = float(rec.loss_scale)
        if not math.isfinite(scale):
            return  # overflow step artifacts; the loss check covers these
        prev = self._prev_scale
        self._prev_scale = scale
        if prev is None:
            return
        if scale < prev:
            self._scale_drops += 1
        elif scale > prev:
            self._scale_drops = 0
            self._scale_collapsed = False
        hit_floor = (scale <= self.loss_scale_floor
                     and prev > self.loss_scale_floor)
        free_fall = self._scale_drops >= self.consecutive_scale_drops
        if (hit_floor or free_fall) and not self._scale_collapsed:
            self._scale_collapsed = True
            why = ("hit the floor" if hit_floor else
                   f"halved {self._scale_drops} steps in a row")
            out.append(HealthEvent(
                "loss_scale_collapse", SEV_CRITICAL, rec.step,
                f"step {rec.step}: fp16 loss scale {scale:.4g} {why} "
                f"(every recent step overflowed)",
                scale, self.loss_scale_floor))

    def _compile_dominated(self, rec: StepRecord) -> bool:
        try:
            compile_ms = float(rec.extra.get("compile_ms", 0.0) or 0.0)
        except (AttributeError, TypeError, ValueError):
            return False
        step_ms = float(rec.step_time_ms)
        return (compile_ms > 0.0 and step_ms > 0.0
                and compile_ms >= self.compile_dominated_frac * step_ms)

    def _check_throughput(self, rec: StepRecord,
                          out: List[HealthEvent]) -> None:
        tps = float(rec.tokens_per_sec)
        if not (math.isfinite(tps) and tps > 0):
            return  # async records carry no rates
        if self._compile_dominated(rec):
            # the step spent its time in XLA lower/compile, not in the
            # program: real progress (the watchdog agrees), but neither a
            # regression to alert on nor a baseline sample to keep —
            # StepRecord.extra["compile_ms"] carries the attribution
            return
        if len(self._tps) >= self.min_points:
            med = _median(list(self._tps))
            if med > 0 and tps < self.throughput_frac * med:
                out.append(HealthEvent(
                    "throughput_regression", SEV_WARNING, rec.step,
                    f"step {rec.step}: {tps:.0f} tokens/s is below "
                    f"{self.throughput_frac:.0%} of the rolling median "
                    f"{med:.0f}", tps / med, self.throughput_frac))
        # regressed samples DO enter the window: a sustained slowdown
        # fires ~min_points events then becomes the new baseline instead
        # of alerting forever
        self._tps.append(tps)

    def _check_recompile_storm(self, rec: StepRecord,
                               out: List[HealthEvent]) -> None:
        if self.recompile_storm_threshold <= 0:
            return
        try:
            n = int(rec.extra.get("recompile_events", 0) or 0)
        except (AttributeError, TypeError, ValueError):
            n = 0
        self._recompiles.append(n)
        storm = sum(self._recompiles)
        if storm >= self.recompile_storm_threshold:
            out.append(HealthEvent(
                "recompile_storm", SEV_WARNING, rec.step,
                f"step {rec.step}: {storm} recompiles within the last "
                f"{len(self._recompiles)} steps — a shape/dtype/static "
                f"leak is re-tracing programs that should be cached "
                f"(see context.compile_programs in the debug bundle)",
                float(storm), float(self.recompile_storm_threshold)))
            # one storm, one event: restart the count so a persistent
            # leak re-alerts per window instead of on every step
            self._recompiles.clear()

    def _check_memory_pressure(self, rec: StepRecord,
                               out: List[HealthEvent]) -> None:
        if self.memory_pressure_frac <= 0:
            return
        frac = None
        try:
            frac = rec.extra.get("hbm_frac")
        except AttributeError:
            frac = None
        if frac is None:
            # fall back to the memory_status fields already on the record
            used = float(rec.memory.get("device_in_use_GB", 0.0) or 0.0)
            limit = float(rec.memory.get("device_limit_GB", 0.0) or 0.0)
            frac = used / limit if limit > 0 else None
        if frac is None:
            return
        frac = float(frac)
        if frac < self.memory_pressure_frac:
            self._pressure_streak = 0
            return
        self._pressure_streak += 1
        if self._pressure_streak < self.memory_pressure_steps:
            return
        out.append(HealthEvent(
            "memory_pressure", SEV_WARNING, rec.step,
            f"step {rec.step}: HBM {frac:.0%} full for "
            f"{self._pressure_streak} consecutive steps (threshold "
            f"{self.memory_pressure_frac:.0%}) — the next shape bump or "
            f"fragmentation event is an OOM; lower micro-batch / raise "
            f"remat / shard further (see memory/pool_* gauges)",
            frac, self.memory_pressure_frac))
        # one streak, one event: restart the count so sustained pressure
        # re-alerts every memory_pressure_steps instead of every step
        self._pressure_streak = 0

    @staticmethod
    def _leaky(series: "collections.deque", frac: float) -> bool:
        """True when the FULL window grew on every consecutive pair AND
        the newest sample clears the rolling median by ``frac`` — flat
        and sawtooth series stay quiet."""
        if len(series) < series.maxlen:
            return False
        xs = list(series)
        if any(b <= a for a, b in zip(xs, xs[1:])):
            return False
        return xs[-1] > _median(xs) * (1.0 + frac)

    def _check_host_leak(self, rec: StepRecord,
                         out: List[HealthEvent]) -> None:
        if self.host_leak_window < 2:
            return
        rss = None
        try:
            rss = rec.extra.get("host_rss_bytes")
        except AttributeError:
            rss = None
        if rss is None and rec.memory.get("process_rss_GB"):
            rss = float(rec.memory["process_rss_GB"]) * 2 ** 30
        if rss is not None:
            self._rss.append(float(rss))
            if self._leaky(self._rss, self.host_leak_frac):
                xs = list(self._rss)
                out.append(HealthEvent(
                    "host_memory_leak", SEV_WARNING, rec.step,
                    f"step {rec.step}: host RSS grew monotonically for "
                    f"{len(xs)} samples ({xs[0] / 2**30:.2f} -> "
                    f"{xs[-1] / 2**30:.2f} GB, "
                    f"{(xs[-1] / max(_median(xs), 1.0) - 1):.1%} over the "
                    f"rolling median) — a host-side buffer (staging, "
                    f"snapshot, un-freed arrays) is accumulating",
                    xs[-1], _median(xs) * (1.0 + self.host_leak_frac)))
                self._rss.clear()  # re-alert per window, not per step
        # live-array COUNT is sampled sparsely (every Nth step) — feed
        # only when present; monotonic count growth is the same leak
        # signature seen from the allocator's side
        live = rec.memory.get("live_buffers")
        if live is None:
            try:
                live = rec.extra.get("live_arrays")
            except AttributeError:
                live = None
        if live is not None:
            self._live.append(float(live))
            if self._leaky(self._live, self.host_leak_frac):
                xs = list(self._live)
                out.append(HealthEvent(
                    "host_memory_leak", SEV_WARNING, rec.step,
                    f"step {rec.step}: live jax-array count grew "
                    f"monotonically for {len(xs)} samples "
                    f"({int(xs[0])} -> {int(xs[-1])}) — arrays are being "
                    f"created without being freed (see `mem top` on a "
                    f"debug bundle for the biggest ones)",
                    xs[-1], _median(xs) * (1.0 + self.host_leak_frac)))
                self._live.clear()

    def _check_numerics(self, rec: StepRecord,
                        out: List[HealthEvent]) -> None:
        """The numerics plane's three rules over the sampled-capture
        summary riding ``extra["numerics"]`` (absent on unsampled steps
        — the streak counters only advance on captures)."""
        try:
            num = rec.extra.get("numerics")
        except AttributeError:
            return
        if not isinstance(num, dict):
            return
        uf = num.get("underflow_frac")
        if self.numerics_underflow_frac > 0 and uf is not None:
            if float(uf) >= self.numerics_underflow_frac:
                self._underflow_streak += 1
                if self._underflow_streak >= self.numerics_underflow_steps:
                    out.append(HealthEvent(
                        "underflow_creep", SEV_WARNING, rec.step,
                        f"step {rec.step}: worst probe underflow fraction "
                        f"{float(uf):.1%} for {self._underflow_streak} "
                        f"consecutive sampled captures (threshold "
                        f"{self.numerics_underflow_frac:.0%}) — tensor "
                        f"tails are creeping toward the dtype flush floor; "
                        f"bump the loss scale (fp16 init_scale) or move "
                        f"the worst probe (`telemetry numerics top`) to "
                        f"fp32 before the gradients silently zero",
                        float(uf), self.numerics_underflow_frac))
                    self._underflow_streak = 0  # re-alert per streak
            else:
                self._underflow_streak = 0
        gmax = num.get("layer_grad_max")
        gmed = num.get("layer_grad_median")
        if (self.numerics_layer_grad_ratio > 0 and gmax is not None
                and gmed is not None):
            floor = self.numerics_layer_grad_floor
            med = max(float(gmed), floor)
            ratio = float(gmax) / med
            if float(gmax) > floor and ratio >= self.numerics_layer_grad_ratio:
                layer = int(num.get("layer_grad_argmax", -1))
                out.append(HealthEvent(
                    "layer_grad_explosion", SEV_WARNING, rec.step,
                    f"step {rec.step}: layer {layer} grad norm "
                    f"{float(gmax):.4g} is {ratio:.0f}x the median "
                    f"layer's {med:.4g} — one layer is diverging ahead "
                    f"of the global clip; check layer {layer}'s inputs "
                    f"and its probes in the last numerics capture",
                    ratio, self.numerics_layer_grad_ratio))
        ent = num.get("gate_entropy_frac", num.get("gate_entropy"))
        if self.numerics_entropy_floor > 0 and ent is not None:
            if float(ent) <= self.numerics_entropy_floor:
                self._entropy_streak += 1
                if self._entropy_streak >= self.numerics_entropy_steps:
                    out.append(HealthEvent(
                        "router_collapse", SEV_WARNING, rec.step,
                        f"step {rec.step}: MoE gating entropy "
                        f"{float(ent):.2f} at/below the "
                        f"{self.numerics_entropy_floor:.2f} floor for "
                        f"{self._entropy_streak} consecutive captures — "
                        f"the router is funneling tokens to the same "
                        f"expert(s); raise the aux-loss coefficient or "
                        f"check moe/load_imbalance",
                        float(ent), self.numerics_entropy_floor))
                    self._entropy_streak = 0
            else:
                self._entropy_streak = 0

    def _check_control_plane(self, rec: StepRecord,
                             out: List[HealthEvent]) -> None:
        """One ``control_plane_degraded`` event per store-outage streak:
        a degraded rendezvous client means heartbeats / tier-2 replica
        publications are BUFFERING (they replay on reconnect) and the
        gang is blind to this node — training itself continues, which is
        exactly why an operator needs the structured alert."""
        if not self.control_plane:
            return
        from ..elasticity.rendezvous import control_plane_status

        st = control_plane_status()
        if not st["degraded"]:
            self._cp_alerted = False
            return
        if self._cp_alerted:
            return
        self._cp_alerted = True
        out.append(HealthEvent(
            "control_plane_degraded", SEV_WARNING, rec.step,
            f"step {rec.step}: rendezvous store unreachable for "
            f"{st['degraded_for_s']:.1f}s ({st['clients']} client(s) "
            f"degraded) — heartbeats and replica-index writes are "
            f"buffered and replay on reconnect; training continues but "
            f"the gang cannot see this node",
            st["degraded_for_s"], 0.0))

    # -- the feed ----------------------------------------------------------

    def observe(self, rec: StepRecord) -> List[HealthEvent]:
        out: List[HealthEvent] = []
        if rec.device_fenced:
            self._check_loss(rec, out)
            self._check_grad_norm(rec, out)
            self._check_loss_scale(rec, out)
        self._check_throughput(rec, out)
        self._check_recompile_storm(rec, out)
        self._check_memory_pressure(rec, out)
        self._check_host_leak(rec, out)
        self._check_numerics(rec, out)
        self._check_control_plane(rec, out)
        for ev in out:
            self._publish(ev)
        return out

    def _publish(self, ev: HealthEvent) -> None:
        self.events_total += 1
        if self.recorder is not None:
            try:
                self.recorder.record_health(ev)
            except Exception as e:  # recorder trouble must not stop checks
                from ..utils.logging import debug_once

                debug_once("health/recorder",
                           f"health-event recording failed ({e!r})")
        reg = self.registry
        if reg is None:
            return
        try:
            reg.counter("health/events_total",
                        "training-health anomaly events").inc()
            reg.counter(f"health/{ev.kind}_total",
                        f"{ev.kind} anomaly events").inc()
            reg.gauge("health/last_event_step",
                      "step of the most recent health event").set(ev.step)
            reg.emit_event("health", ev.to_dict())
        except Exception as e:  # metrics trouble must not stop checks
            from ..utils.logging import debug_once

            debug_once("health/metrics",
                       f"health-event metrics publish failed ({e!r})")
