"""Operator CLI — ``python -m deepspeed_tpu.telemetry <cmd>``.

The read side of the observability plane, for humans at 3am:

* ``collect``  — pull a cluster archive from a LIVE rendezvous store
  (or a shared-filesystem drop dir): request fresh bundles from every
  host, assemble one ``cluster-<utc>/`` archive + manifest.
* ``summary``  — one bundle OR one cluster archive: reason, last N
  steps, health events, slowest spans, desync verdict.
* ``diff``     — two hosts' bundles: step skew, comm-census deltas,
  ledger seq delta (the "which host is behind, doing what" question).
* ``desync``   — offline collective-divergence analysis over an
  archive's ledger tails; names the lagging rank and the first
  mismatched collective.  Exit code 3 when a desync is found (script-
  able), 0 when clean.
* ``perf``     — the perf-regression sentinel (``telemetry/perf``):
  ``perf show`` prints a run's sentinel metrics, ``perf baseline``
  stores them, ``perf check`` compares a run against the stored
  baseline and exits 3 on regression beyond tolerance — the gate that
  turns BENCH_r*.json from a log into a trajectory.  A no-data artifact
  (an r05-style environment failure) is *skipped with a named reason*,
  never a silent pass or a crash.
* ``mem``      — the memory plane (``telemetry/memory``): ``mem show``
  one bundle's pool breakdown, ``mem top`` its largest live arrays,
  ``mem diff`` two bundles with a leak verdict (exit 3).
* ``top``      — the LIVE cluster view (``telemetry/rollup.py``):
  per-node step / step-time EWMA / goodput / hbm / heartbeat age /
  store-outage counters rendered straight from the rendezvous store's
  rollup publications — no bundle collection, no engine.  ``--once``
  prints one frame and exits 0 (scriptable); default refreshes.

Every command except ``collect``/``top`` works on plain directories —
no store, no JAX device needed beyond what importing the package costs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from .aggregator import (CLUSTER_MANIFEST, CLUSTER_TRACE,
                         build_cluster_manifest, collect_cluster_archive,
                         collect_cluster_archive_fs, load_host_manifests)
from .collective_ledger import (find_first_divergence,
                                format_divergence_report)
from .flight_recorder import BUNDLE_MANIFEST, BUNDLE_TRACE


def _fail(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


def _is_bundle(path: str) -> bool:
    return os.path.exists(os.path.join(path, BUNDLE_MANIFEST))


def _is_archive(path: str) -> bool:
    return (os.path.exists(os.path.join(path, CLUSTER_MANIFEST))
            or os.path.isdir(os.path.join(path, "hosts")))


def _resolve_bundle(path: str) -> Optional[str]:
    """Accept a bundle dir, or a dir holding exactly one ``bundle-*``
    (a host dir inside an archive, or a one-trip dump dir)."""
    if _is_bundle(path):
        return path
    if os.path.isdir(path):
        cands = sorted(d for d in os.listdir(path)
                       if _is_bundle(os.path.join(path, d)))
        if cands:
            return os.path.join(path, cands[-1])  # newest by name stamp
    return None


def _load_manifest(bundle: str) -> Dict[str, Any]:
    with open(os.path.join(bundle, BUNDLE_MANIFEST)) as fh:
        return json.load(fh)


def _slowest_spans(bundle: str, n: int = 5) -> List[Dict[str, Any]]:
    p = os.path.join(bundle, BUNDLE_TRACE)
    if not os.path.exists(p):
        return []
    try:
        with open(p) as fh:
            events = json.load(fh).get("traceEvents", [])
    except (OSError, ValueError):
        return []
    spans = [e for e in events if isinstance(e.get("dur"), (int, float))]
    spans.sort(key=lambda e: -e["dur"])
    return spans[:n]


# ---------------------------------------------------------------------------
# summary
# ---------------------------------------------------------------------------

def _print_bundle_summary(bundle: str, last_n: int) -> None:
    m = _load_manifest(bundle)
    print(f"bundle: {bundle}")
    print(f"  reason: {m.get('reason')}")
    print(f"  host: {m.get('host')}  pid: {m.get('pid')}  "
          f"time: {m.get('time_utc')}")
    steps = m.get("steps") or []
    print(f"  steps recorded: {len(steps)}")
    for s in steps[-last_n:]:
        print(f"    step {s.get('step')}: loss={s.get('loss')} "
              f"step_time_ms={s.get('step_time_ms')} "
              f"tokens/s={s.get('tokens_per_sec')}")
    health = m.get("health_events") or []
    print(f"  health events: {len(health)}")
    for h in health[-last_n:]:
        print(f"    {h.get('kind')}@step {h.get('step')}: "
              f"{h.get('message', '')}")
    led = (m.get("context") or {}).get("collective_ledger")
    if isinstance(led, dict):
        print(f"  collective ledger: seq {led.get('seq')} "
              f"tail_hash {led.get('tail_hash')} "
              f"(tail of {len(led.get('tail') or [])})")
        if led.get("exec_seq"):
            print(f"  exec-order census: seq {led.get('exec_seq')} "
                  f"tail_hash {led.get('exec_tail_hash')}")
    mem = (m.get("context") or {}).get("memory")
    if isinstance(mem, dict):
        from .memory.oom import _fmt_bytes, top_pools_of

        dev = mem.get("device") or {}
        line = "  memory:"
        if dev.get("bytes_limit"):
            line += (f" hbm {_fmt_bytes(dev.get('bytes_in_use', 0))}/"
                     f"{_fmt_bytes(dev['bytes_limit'])}")
        if mem.get("host_rss_bytes") is not None:
            line += f" rss {_fmt_bytes(mem['host_rss_bytes'])}"
        if mem.get("tracked_bytes"):
            line += f" tracked {_fmt_bytes(mem['tracked_bytes'])}"
        top = top_pools_of(mem)
        if top:
            line += " — top: " + ", ".join(
                f"{p}={_fmt_bytes(n)}" for p, n in top)
        print(line)
        if mem.get("device_unresponsive"):
            print(f"    DEVICE UNRESPONSIVE: {mem['device_unresponsive']}")
    gp = (m.get("context") or {}).get("goodput")
    if isinstance(gp, dict):
        buckets = gp.get("buckets_s") or {}
        budget = "  ".join(f"{k}={v:.1f}s" for k, v in sorted(
            buckets.items()) if v)
        print(f"  goodput: {gp.get('goodput')} "
              f"(rolling {gp.get('rolling_goodput')})"
              + (f" — {budget}" if budget else ""))
    ct = (m.get("context") or {}).get("compile_programs")
    if isinstance(ct, dict):
        print(f"  compiles: {ct.get('events_total')} events "
              f"({ct.get('recompiles_total')} recompiles, "
              f"{float(ct.get('time_ms_total') or 0) / 1e3:.1f}s)")
        for site, progs in sorted((ct.get("sites") or {}).items()):
            for p in progs:
                if p.get("kind") != "recompile":
                    continue
                from .perf.compile_tracker import format_cause

                causes = "; ".join(
                    format_cause(c) for c in (p.get("causes") or [])[:3])
                print(f"    RECOMPILE {site} #{p.get('program')}: "
                      f"{causes or 'unknown cause'}")
    spans = _slowest_spans(bundle)
    if spans:
        print("  slowest spans:")
        for e in spans:
            print(f"    {e.get('name')}: {e['dur'] / 1e3:.3f} ms")
    ann = m.get("annotations") or []
    if ann:
        print(f"  annotations: {len(ann)} "
              f"(last: {ann[-1].get('kind')})")


def _print_archive_summary(archive: str, last_n: int) -> int:
    mp = os.path.join(archive, CLUSTER_MANIFEST)
    if os.path.exists(mp):
        with open(mp) as fh:
            cm = json.load(fh)
    else:  # hand-assembled archive (shared-FS copy) — compute in memory;
        # summary is a READ command and must work on a read-only mount
        cm = build_cluster_manifest(archive, persist=False)
    print(f"cluster archive: {archive}")
    print(f"  created: {cm.get('created_utc')}  "
          f"hosts: {len(cm.get('hosts') or {})}  "
          f"missing: {cm.get('missing_hosts') or 'none'}")
    ct_path = os.path.join(archive, CLUSTER_TRACE)
    if os.path.exists(ct_path):
        try:
            with open(ct_path) as fh:
                hosts_meta = (json.load(fh).get("metadata")
                              or {}).get("hosts") or {}
            aligned = sum(1 for h in hosts_meta.values()
                          if h.get("aligned"))
            print(f"  merged trace: {CLUSTER_TRACE} "
                  f"({len(hosts_meta)} lanes, {aligned} clock-aligned)")
        except (OSError, ValueError):
            print(f"  merged trace: {CLUSTER_TRACE} (unreadable)")
    partials = cm.get("partials") or {}
    for node in cm.get("missing_hosts") or []:
        p = partials.get(node)
        if p:
            live = p.get("liveness") or {}
            print(f"  [{node}] PARTIAL only (watchdog trip "
                  f"#{p.get('trips')}): step {live.get('step')} "
                  f"coll_seq {live.get('coll_seq')} — see "
                  f"hosts/{node}/partial.json")
    print(f"  step skew across hosts: {cm.get('step_skew')}")
    if cm.get("goodput_min") is not None:
        print(f"  cluster goodput: min {cm.get('goodput_min')} "
              f"mean {round(cm.get('goodput_mean'), 4)}")
    for node, h in sorted((cm.get("hosts") or {}).items()):
        gp = (f" goodput {h.get('goodput')}"
              if h.get("goodput") is not None else "")
        mem = h.get("memory") or {}
        mm = (f" hbm {mem['hbm_frac']:.0%}"
              if mem.get("hbm_frac") is not None else "")
        print(f"  [{node}] step {h.get('last_step')} "
              f"ledger_seq {h.get('ledger_seq')} "
              f"comm_ops {h.get('comm_ops')}{gp}{mm} — {h.get('reason')}")
        if mem.get("device_unresponsive"):
            print(f"    [{node}] DEVICE UNRESPONSIVE: "
                  f"{mem['device_unresponsive']}")
    deltas = cm.get("comm_census_delta") or {}
    skewed = {op: d for op, d in deltas.items() if d.get("delta")}
    if skewed:
        print("  comm census deltas (op: max-min call count):")
        for op, d in sorted(skewed.items()):
            print(f"    {op}: {d['delta']} {d['per_host']}")
    print("  desync analysis:")
    for line in (cm.get("desync_report") or "").splitlines():
        print(f"    {line}")
    hosts_dir = os.path.join(archive, "hosts")
    if os.path.isdir(hosts_dir):
        for node in sorted(os.listdir(hosts_dir)):
            b = _resolve_bundle(os.path.join(hosts_dir, node))
            if b:
                print()
                _print_bundle_summary(b, last_n)
    return 0


def cmd_summary(args: argparse.Namespace) -> int:
    path = args.path
    if _is_archive(path):
        return _print_archive_summary(path, args.steps)
    bundle = _resolve_bundle(path)
    if bundle is None:
        return _fail(f"{path}: neither a debug bundle nor a cluster archive")
    _print_bundle_summary(bundle, args.steps)
    return 0


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------

def cmd_diff(args: argparse.Namespace) -> int:
    a, b = _resolve_bundle(args.a), _resolve_bundle(args.b)
    if a is None or b is None:
        return _fail("diff needs two debug bundle directories")
    ma, mb = _load_manifest(a), _load_manifest(b)

    def last_step(m):
        steps = m.get("steps") or []
        return steps[-1].get("step") if steps else None

    la, lb = last_step(ma), last_step(mb)
    print(f"A: {a}\n   reason: {ma.get('reason')}  last step: {la}")
    print(f"B: {b}\n   reason: {mb.get('reason')}  last step: {lb}")
    if isinstance(la, (int, float)) and isinstance(lb, (int, float)):
        print(f"step skew (A-B): {la - lb}")
    ca = (ma.get("comm") or {}).get("summary") or {}
    cb = (mb.get("comm") or {}).get("summary") or {}
    ops = sorted(set(ca) | set(cb))
    if ops:
        print("comm census (op: A count / B count / delta):")
        for op in ops:
            na = float((ca.get(op) or {}).get("count", 0))
            nb = float((cb.get(op) or {}).get("count", 0))
            print(f"  {op}: {na:g} / {nb:g} / {na - nb:+g}")
    la_led = (ma.get("context") or {}).get("collective_ledger") or {}
    lb_led = (mb.get("context") or {}).get("collective_ledger") or {}
    if la_led or lb_led:
        print(f"collective ledger: A seq {la_led.get('seq')} "
              f"hash {la_led.get('tail_hash')} | "
              f"B seq {lb_led.get('seq')} hash {lb_led.get('tail_hash')}")
        tails = {}
        if la_led.get("tail"):
            tails["A"] = la_led["tail"]
        if lb_led.get("tail"):
            tails["B"] = lb_led["tail"]
        if len(tails) == 2:
            print(format_divergence_report(find_first_divergence(tails)))
    return 0


# ---------------------------------------------------------------------------
# desync
# ---------------------------------------------------------------------------

def cmd_desync(args: argparse.Namespace) -> int:
    if not _is_archive(args.archive):
        return _fail(f"{args.archive}: not a cluster archive")
    manifests = load_host_manifests(args.archive)
    if not manifests:
        return _fail(f"{args.archive}: no host bundles found")
    # same filter as the cluster manifest (aggregator._ledger_tails):
    # a host whose bundle has NO ledger context (ledger off / pre-ledger
    # bundle) must not enter the analysis as an empty ledger — it would
    # read as "lagging by everything".  A PRESENT-but-empty tail is real
    # data ("this host never issued a collective") and stays in.
    tails = {}
    no_ledger = []
    for node, m in manifests.items():
        tail = ((m.get("context") or {}).get("collective_ledger") or {}) \
            .get("tail")
        if isinstance(tail, list):
            tails[node] = tail
        else:
            no_ledger.append(node)
    if no_ledger:
        print(f"(no ledger data from: {', '.join(sorted(no_ledger))} — "
              f"excluded from the analysis)")
    report = find_first_divergence(tails)
    print(format_divergence_report(report))
    return 3 if report.get("desync") else 0


# ---------------------------------------------------------------------------
# collect
# ---------------------------------------------------------------------------

def cmd_collect(args: argparse.Namespace) -> int:
    if args.shared_fs:
        archive = collect_cluster_archive_fs(args.shared_fs,
                                             out_dir=args.out)
        print(archive)
        return 0
    if not args.endpoint:
        return _fail("collect needs --endpoint host:port (live store) "
                     "or --shared-fs <dir>")
    from ..elasticity.rendezvous import RendezvousClient

    client = RendezvousClient(args.endpoint)
    peers = ([p for p in args.peers.split(",") if p]
             if args.peers else None)
    try:
        archive = collect_cluster_archive(
            client, peer_ids=peers, out_dir=args.out,
            timeout_s=args.timeout, request=not args.no_request)
    except (ValueError, ConnectionError, OSError) as e:
        return _fail(str(e))
    print(archive)
    return 0


# ---------------------------------------------------------------------------
# top — the live cluster view (ISSUE 13)
# ---------------------------------------------------------------------------

def _render_serving_rows(client: Any, silent_after_s: float = 30.0
                         ) -> str:
    """The serving-worker table for ``top --serving`` (ISSUE 15
    satellite): registered workers (``serving/srv/*``), endpoint
    health from heartbeat age, live load from the rollup-labeled
    gauges each worker publishes.  Everything is already in the store
    — this just renders it."""
    from .aggregator import _heartbeat_view
    from .rollup import collect_rollup

    # lazy: the serving plane is optional at `top` time
    from ..serving.worker import SRV_PREFIX

    regs: Dict[str, Dict[str, Any]] = {}
    for key in sorted(client.keys(SRV_PREFIX)):
        v = client.get(key)
        if isinstance(v, dict):
            regs[key[len(SRV_PREFIX):]] = v

    def _slo_block() -> str:
        # the front door publishes serving/slo_* gauges through the
        # same rollup (ISSUE 16) — collect over every publisher, not
        # just registered workers, or the door's lane is invisible
        from ..serving.slo import render_slo_table, slo_rows_from_rollup

        pub = sorted(k.rsplit("/", 1)[1]
                     for k in client.keys("telemetry/metrics/"))
        if not pub:
            return ""
        rows = slo_rows_from_rollup(collect_rollup(client, pub))
        return render_slo_table(rows) if rows else ""

    if not regs:
        slo = _slo_block()
        return ("serving workers: none registered"
                + ("\n\n" + slo if slo else ""))
    ids = sorted(regs)
    rollup = collect_rollup(client, ids)
    hb = _heartbeat_view(client, ids)
    lines = [f"{'WORKER':<14} {'ROLE':<8} {'ENDPOINT':<22} "
             f"{'ACTIVE':>6} {'QUEUED':>6} {'TOK/S':>8} {'REQS':>7} "
             f"{'HB_AGE':>7} {'STATE':<8}"]

    def g(doc, name):
        snap = (doc or {}).get("snapshot") or {}
        m = (snap.get("gauges") or {}).get(name)
        return None if m is None else float(m.get("value", 0.0))

    def c(doc, name):
        snap = (doc or {}).get("snapshot") or {}
        m = (snap.get("counters") or {}).get(name)
        return None if m is None else float(m.get("value", 0.0))

    from .rollup import _fmt

    for wid in ids:
        reg = regs[wid]
        doc = rollup.node_doc(wid)
        age = (hb.get(wid) or {}).get("age_s")
        state = ("SILENT" if age is None or age > silent_after_s
                 else "LIVE")
        reqs = (c(doc, "serving/worker_requests_total")
                or c(doc, "serving/worker_prefills_total"))
        lines.append(
            f"{wid:<14} {str(reg.get('role', '?')):<8} "
            f"{str(reg.get('endpoint', '?')):<22} "
            f"{_fmt(g(doc, 'serving/worker_active'), '{:.0f}'):>6} "
            f"{_fmt(g(doc, 'serving/worker_queued'), '{:.0f}'):>6} "
            f"{_fmt(g(doc, 'serving/worker_tok_s'), '{:.1f}'):>8} "
            f"{_fmt(reqs, '{:.0f}'):>7} "
            f"{_fmt(age, '{:.1f}'):>7} "
            f"{state:<8}")
    slo = _slo_block()
    if slo:
        lines.append("")
        lines.append(slo)
    return "\n".join(lines)


def _render_top_frame(client: Any, peers: Optional[List[str]],
                      endpoint: str, silent_after_s: float = 30.0,
                      serving: bool = False) -> str:
    from .aggregator import _heartbeat_view, sealed_members
    from .rollup import collect_rollup, render_top

    peer_ids = peers or sealed_members(client)
    if not peer_ids:
        # no sealed round yet: fall back to whoever has published
        # telemetry (a gang mid-formation is still worth watching)
        peer_ids = sorted(k.rsplit("/", 1)[1]
                          for k in client.keys("telemetry/metrics/"))
    if not peer_ids and not serving:
        raise ValueError("no peers: store has no sealed round and no "
                         "telemetry publications (pass --peers)")
    frame = ""
    if peer_ids:
        rollup = collect_rollup(client, peer_ids)
        hb = _heartbeat_view(client, peer_ids)
        store_info = {"endpoint": endpoint,
                      "generation": client.get("srv/gen"),
                      "round": client.get("rdzv/round")}
        frame = render_top(rollup, hb_view=hb, store_info=store_info,
                           silent_after_s=silent_after_s)
    if serving:
        block = _render_serving_rows(client,
                                     silent_after_s=silent_after_s)
        frame = (frame + "\n\n" + block) if frame else block
    return frame


def cmd_top(args: argparse.Namespace) -> int:
    if not args.endpoint:
        return _fail("top needs --endpoint host:port "
                     "(or $DS_RDZV_ENDPOINT)")
    import time as _time

    from ..elasticity.rendezvous import RendezvousClient

    client = RendezvousClient(args.endpoint, retries=1, backoff_s=0.05)
    peers = [p for p in (args.peers or "").split(",") if p] or None
    frames = 0
    try:
        while True:
            try:
                frame = _render_top_frame(client, peers, args.endpoint,
                                          silent_after_s=args.silent_after,
                                          serving=getattr(args, "serving",
                                                          False))
            except (ValueError, ConnectionError, OSError) as e:
                return _fail(f"top: {e}")
            if frames:
                print()  # frame separator (no TTY games — pipe-friendly)
            print(f"--- {_time.strftime('%H:%M:%S')}")
            print(frame, flush=True)
            frames += 1
            if args.once or (args.frames and frames >= args.frames):
                return 0
            _time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0


# ---------------------------------------------------------------------------
# perf — the regression sentinel
# ---------------------------------------------------------------------------

def cmd_perf(args: argparse.Namespace) -> int:
    from .perf import baseline as perfmod

    try:
        run = perfmod.load_run(args.run)
    except (OSError, ValueError) as e:
        return _fail(f"perf {args.perf_cmd}: {e}")
    metrics = perfmod.extract_perf(run)

    if args.perf_cmd == "show":
        # satellite (ISSUE 13): an environment-failure artifact (r05's
        # dead tunnel — value 0.0 + error, or the explicit marker) is a
        # SKIPPED round and must say so — `check` already understood
        # the marker, but `show` used to render 0.0 as if measured
        reason = perfmod.environment_failure_reason(run)
        if reason:
            print(f"run: {args.run}")
            print(f"  SKIPPED round — environment failure: {reason}")
            print("  (no metrics were measured; values in this artifact "
                  "are placeholders, not results)")
            return 0
        if not metrics:
            return _fail(f"{args.run}: no sentinel metrics "
                         f"({', '.join(perfmod.PERF_METRICS)})")
        print(f"run: {args.run}")
        for name in perfmod.PERF_METRICS:
            if name in metrics:
                print(f"  {name}: {metrics[name]:g}")
        return 0

    if args.perf_cmd == "baseline":
        try:
            doc = perfmod.save_baseline(args.out, run, source=args.run)
        except ValueError as e:
            return _fail(str(e))
        print(f"baseline written: {args.out} "
              f"({', '.join(sorted(doc['metrics']))})")
        return 0

    # check
    if not metrics:
        # a run that produced NO sentinel metrics: an environment
        # failure (r05: dead tunnel, value 0.0 + error) is a SKIP with a
        # named reason — the bench never ran, so there is nothing to
        # gate; anything else stays an error (a healthy run without
        # metrics is a wiring bug the operator must see)
        reason = perfmod.environment_failure_reason(run)
        if reason:
            print(f"perf check SKIPPED: run artifact carries no data — "
                  f"environment failure ({reason}); nothing to gate")
            return 0
        return _fail(f"{args.run}: no sentinel metrics and no "
                     f"environment-failure marker — not a bench artifact?")
    try:
        base = perfmod.load_baseline(args.baseline)
    except OSError as e:
        return _fail(f"perf check: cannot read baseline "
                     f"{args.baseline} ({e}); run `perf baseline` first")
    try:
        tol = perfmod.parse_tolerances(args.tol)
    except ValueError as e:
        return _fail(str(e))
    result = perfmod.check_regression(metrics, base, tolerances=tol)
    print(perfmod.format_check_report(result))
    if not result["compared"]:
        return _fail("perf check: run and baseline share no metrics")
    if result["regressions"]:
        print(f"PERF REGRESSION: {len(result['regressions'])} metric(s) "
              f"beyond tolerance vs {args.baseline}")
        return 3
    print("perf check passed")
    return 0


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry",
        description="cluster observability: collect / summarize / diff "
                    "debug bundles, analyze collective desync")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("collect", help="pull a cluster archive from a live "
                                       "rendezvous store or a shared FS dir")
    c.add_argument("--endpoint", default=os.environ.get("DS_RDZV_ENDPOINT"),
                   help="rendezvous store host:port "
                        "(default: $DS_RDZV_ENDPOINT)")
    c.add_argument("--peers", default="",
                   help="comma-separated node ids (default: the store's "
                        "current sealed round)")
    c.add_argument("--out", default="cluster_archives")
    c.add_argument("--timeout", type=float, default=30.0)
    c.add_argument("--no-request", action="store_true",
                   help="take already-published bundles as-is instead of "
                        "requesting fresh dumps")
    c.add_argument("--shared-fs", default="",
                   help="assemble from a shared-filesystem drop dir "
                        "instead of a live store")
    c.set_defaults(fn=cmd_collect)

    s = sub.add_parser("summary", help="summarize a bundle or archive")
    s.add_argument("path")
    s.add_argument("--steps", type=int, default=5,
                   help="last N steps/events to print")
    s.set_defaults(fn=cmd_summary)

    d = sub.add_parser("diff", help="compare two hosts' bundles")
    d.add_argument("a")
    d.add_argument("b")
    d.set_defaults(fn=cmd_diff)

    y = sub.add_parser("desync", help="offline collective-divergence "
                                      "analysis over an archive "
                                      "(exit 3 when desync found)")
    y.add_argument("archive")
    y.set_defaults(fn=cmd_desync)

    t = sub.add_parser("top", help="live cluster view from the store's "
                                   "metrics rollup (no bundles)")
    t.add_argument("--endpoint", default=os.environ.get("DS_RDZV_ENDPOINT"),
                   help="rendezvous store host:port "
                        "(default: $DS_RDZV_ENDPOINT)")
    t.add_argument("--peers", default="",
                   help="comma-separated node ids (default: the store's "
                        "current sealed round, else every publishing node)")
    t.add_argument("--once", action="store_true",
                   help="print one frame and exit 0")
    t.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    t.add_argument("--frames", type=int, default=0,
                   help="stop after N frames (0 = until interrupted)")
    t.add_argument("--silent-after", type=float, default=30.0,
                   help="heartbeat age (s) past which a node renders "
                        "SILENT")
    t.add_argument("--serving", action="store_true",
                   help="also render registered serving workers (role, "
                        "endpoint health, active/queued, tok/s) from "
                        "the store")
    t.set_defaults(fn=cmd_top)

    from .perf.baseline import DEFAULT_BASELINE

    f = sub.add_parser("perf", help="perf-regression sentinel: show/"
                                    "baseline/check bench runs "
                                    "(check exits 3 on regression)")
    fsub = f.add_subparsers(dest="perf_cmd", required=True)
    fs = fsub.add_parser("show", help="print a run's sentinel metrics")
    fs.add_argument("run", help="bench JSON line, BENCH_r*.json artifact, "
                                "or saved baseline")
    fs.set_defaults(fn=cmd_perf)
    fb = fsub.add_parser("baseline", help="store a run as the baseline")
    fb.add_argument("run")
    fb.add_argument("--out", default=DEFAULT_BASELINE,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    fb.set_defaults(fn=cmd_perf)
    fc = fsub.add_parser("check", help="compare a run vs the baseline; "
                                       "exit 3 on regression")
    fc.add_argument("run")
    fc.add_argument("--baseline", default=DEFAULT_BASELINE)
    fc.add_argument("--tol", action="append", default=[],
                    metavar="METRIC=FRAC",
                    help="override a tolerance, e.g. --tol mfu=0.05 "
                         "(repeatable)")
    fc.set_defaults(fn=cmd_perf)

    from .memory.cli import add_mem_parser

    add_mem_parser(sub)

    from .anatomy.cli import add_anatomy_parser

    add_anatomy_parser(sub)

    from .numerics.cli import add_numerics_parser

    add_numerics_parser(sub)

    from .profiler.cli import add_profile_parser

    add_profile_parser(sub)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)
