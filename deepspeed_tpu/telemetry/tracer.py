"""Host-side span tracer — nested timed regions, Chrome-trace export.

Role: the correlation layer the reproduction lacked (ISSUE 1).  The
device side of every hot path is already observable through
``profiling/collective_trace.py`` (XLA lanes under ``jax.profiler``);
this module adds the HOST side — ``telemetry.span("zero/all_gather")``
around dispatch/placement/IO work — and exports the same Chrome-trace
JSON event shape (``ph: "X"`` duration events, microsecond timestamps)
so both can be loaded into one Perfetto/chrome://tracing view and read
against each other.

Spans nest per thread (a thread-local stack carries depth and parent),
are bounded in memory (``max_events`` ring), and can optionally close
with a device fence so a span around dispatched device work measures
execution, not enqueue.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional


def device_fence(value=None) -> None:
    """Best-effort device drain.  ``jax.effects_barrier()`` only flushes
    EFFECTS (debug callbacks, io) — it does NOT wait for dispatched pure
    computations, so pass the ``value`` a span's work produced to get a
    real execution fence (``block_until_ready`` on it); the only fully
    reliable fence on tunneled platforms is fetching a dependent scalar
    (see bench.py ``_sync``), which only the caller can do."""
    try:
        import jax

        if value is not None:
            jax.block_until_ready(value)
        jax.effects_barrier()
    except Exception as e:  # fence failure ⇒ host-time spans, say so once
        from ..utils.logging import debug_once

        debug_once("tracer/device_fence",
                   f"device fence failed ({e!r}); span timings reflect "
                   f"dispatch, not device completion")


class SpanTracer:
    """Bounded in-memory span buffer with Chrome-trace JSON export."""

    def __init__(self, max_events: int = 100_000):
        self.max_events = int(max_events)
        #: ring: once full, the OLDEST span is evicted — a long run's
        #: export keeps the window around its end (stalls near the end of
        #: a run are what traces get opened for)
        self._events: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=self.max_events)
        self._dropped = 0
        self._lock = threading.Lock()
        self._tls = threading.local()
        #: one stable origin so span timestamps are comparable across
        #: threads (perf_counter has an arbitrary epoch per process)
        self._t0 = time.perf_counter()
        #: store-clock mapping (telemetry/clocksync.py): set when this
        #: process estimated its offset to the rendezvous store clock —
        #: exported in the trace metadata so N hosts' traces merge onto
        #: ONE timeline (``telemetry collect`` -> cluster_trace.json)
        self._clock_sync: Optional[Dict[str, Any]] = None

    @property
    def max_events(self) -> int:
        return self._max_events

    @max_events.setter
    def max_events(self, n: int) -> None:
        self._max_events = int(n)
        ring = getattr(self, "_events", None)
        if ring is not None and ring.maxlen != self._max_events:
            self._events = collections.deque(ring, maxlen=self._max_events)

    # ------------------------------------------------------------------

    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1  # ring full: oldest event falls off
            self._events.append(ev)

    @contextmanager
    def span(self, name: str, fence: bool = False,
             args: Optional[Dict[str, Any]] = None):
        """Time a nested region.  ``fence=True`` flushes jax EFFECTS
        before the end stamp; dispatched pure computations are only
        fenced by blocking on their results — do that INSIDE the span
        (``jax.block_until_ready(out)`` / a dependent scalar fetch) when
        the span must measure execution rather than enqueue."""
        stack = self._stack()
        stack.append(name)
        start = time.perf_counter()
        try:
            yield self
        finally:
            if fence:
                device_fence()
            end = time.perf_counter()
            stack.pop()
            ev = {
                "ph": "X", "cat": "host", "name": name,
                "pid": os.getpid(), "tid": threading.get_ident(),
                "ts": round((start - self._t0) * 1e6, 1),
                "dur": round((end - start) * 1e6, 1),
            }
            span_args = dict(args or {})
            span_args["depth"] = len(stack)
            if stack:
                span_args["parent"] = stack[-1]
            ev["args"] = span_args
            self._append(ev)

    # ------------------------------------------------------------------

    def set_clock_sync(self, offset_s: float, rtt_s: Optional[float] = None,
                       generation: Any = None,
                       node_id: Optional[str] = None) -> None:
        """Record this process's estimated offset to the store clock
        (``store_time ~= perf_counter() + offset_s``).  Span ``ts``
        values stay in the tracer's private timebase; the metadata
        carries ``trace_to_store_offset_us`` so any consumer can shift
        ``ev.ts + trace_to_store_offset_us`` onto the shared store
        timeline — that arithmetic is what clock-aligns the per-process
        lanes in ``cluster_trace.json``."""
        with self._lock:
            self._clock_sync = {
                "offset_s": float(offset_s),
                "rtt_s": None if rtt_s is None else float(rtt_s),
                "generation": generation,
                "node_id": node_id,
                # ts (us since _t0) + this = us on the STORE clock
                "trace_to_store_offset_us": round(
                    (self._t0 + float(offset_s)) * 1e6, 1),
            }

    def clock_sync(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._clock_sync) if self._clock_sync else None

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        return self._dropped

    def reset(self) -> None:
        with self._lock:
            self._events = collections.deque(maxlen=self._max_events)
            self._dropped = 0

    def chrome_trace(self) -> Dict[str, Any]:
        """The ``{"traceEvents": [...]}`` document chrome://tracing and
        Perfetto load; the ``X`` event shape matches what
        ``profiling/collective_trace.parse_trace`` consumes from the XLA
        profiler, so host spans and device lanes merge into one view."""
        meta: Dict[str, Any] = {"source": "deepspeed_tpu.telemetry",
                                "dropped_events": self._dropped}
        sync = self.clock_sync()
        if sync is not None:
            meta["clock_sync"] = sync
        return {"traceEvents": self.events(),
                "displayTimeUnit": "ms",
                "metadata": meta}

    def save_chrome_trace(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.chrome_trace(), fh)
        os.replace(tmp, path)  # atomic: a crashed flush never tears the file
        return path


@contextmanager
def _noop_cm():
    yield None


NOOP_SPAN = _noop_cm
