"""Cross-process metrics rollup — N registries, ONE merged view.

Every observability layer through PR 12 assumed one process and one
shared :class:`~.metrics.MetricsRegistry`; PR 11's chaos gang runs N
real Python processes whose registries can only meet through the
rendezvous store.  This module is that meeting point (ISSUE 13
tentpole):

* **publish side** (every worker): :func:`push_node_telemetry` ships
  the local registry's :meth:`~.metrics.MetricsRegistry.snapshot` plus
  a batch of compact :class:`StepStream` records to the store under
  ``telemetry/{metrics,steps}/<node>`` — on the existing heartbeat
  transport, at a configurable cadence, degraded-mode tolerant (a
  store outage leaves records in the bounded ring; the next healthy
  push flushes them exactly once — the consumer dedups by sequence).
* **rollup side** (rank 0 / the operator): :class:`MetricsRollup`
  ingests every node's documents and renders ONE merged Prometheus
  export where **every sample carries a node label** (collision between
  node-local and rolled-up series is impossible by construction: the
  rollup never emits an unlabeled sample, and gang aggregates use the
  reserved ``node="_cluster"`` label value — a real node that dares to
  call itself ``_cluster`` is remapped).  Counters and histograms also
  get summed ``_cluster`` aggregates; gauges stay per-node (summing a
  gauge is a lie).
* **live view**: ``python -m deepspeed_tpu.telemetry top`` renders the
  rollup straight from the store — per-node step / step-time EWMA /
  goodput / hbm / heartbeat age / store health — without collecting a
  single bundle.

Store keys (all JSON values through ``RendezvousClient``)::

    telemetry/metrics/<node>   {v, node, seq, stream, clock, snapshot}
    telemetry/steps/<node>     {v, node, stream, records: [{seq, ...}]}

Neither key is write-journaled: snapshots are absolute state (a replay
of a stale one after a store restart would only regress the view until
the next cadence tick) and step batches are deduped by ``(stream,
seq)`` on ingest, so the at-least-once transport still counts each
record exactly once.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import debug_once, logger
from .metrics import escape_help, format_labels, prom_name

#: rollup document schema version
ROLLUP_SCHEMA_V = 1

#: reserved node-label value for gang-wide aggregate samples; a real
#: node id equal to it is remapped (collision-free by construction)
CLUSTER_NODE_LABEL = "_cluster"


def _metrics_key(node_id: str) -> str:
    return f"telemetry/metrics/{node_id}"


def _steps_key(node_id: str) -> str:
    return f"telemetry/steps/{node_id}"


def node_label_value(node_id: str) -> str:
    """The label value a node's samples carry — never the reserved
    aggregate value."""
    nid = str(node_id)
    return nid + ":node" if nid == CLUSTER_NODE_LABEL else nid


# ---------------------------------------------------------------------------
# step streaming (publish side)
# ---------------------------------------------------------------------------

#: compact per-step fields shipped to the rollup — the operator-facing
#: subset, NOT the full StepRecord (bundles carry that)
STEP_STREAM_FIELDS = ("step", "loss", "step_time_ms", "tokens_per_sec")


class StepStream:
    """Bounded ring of compact step records awaiting shipment.

    ``push`` assigns a monotonically increasing sequence number;
    ``unacked`` returns everything not yet confirmed shipped; ``ack``
    advances the shipped watermark.  A store outage simply leaves the
    ring growing (bounded — the oldest unshipped records fall off and
    are counted) until the next healthy push flushes it; the consumer
    dedups by ``(stream, seq)`` so a retried batch never double-counts.
    """

    def __init__(self, maxlen: int = 256, enabled: bool = False):
        self.enabled = bool(enabled)
        self.maxlen = int(maxlen)
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=self.maxlen)
        self._seq = 0
        self._acked = 0
        self.dropped = 0
        #: distinguishes this process's sequence space from a restarted
        #: predecessor's under the same node id (the consumer resets its
        #: watermark when the stream id changes)
        self.stream_id = f"{os.getpid()}-{time.time_ns()}"
        self._lock = threading.Lock()

    def configure(self, enabled: Optional[bool] = None,
                  maxlen: Optional[int] = None) -> "StepStream":
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if maxlen is not None and int(maxlen) != self.maxlen:
                self.maxlen = int(maxlen)
                self._ring = collections.deque(self._ring,
                                               maxlen=self.maxlen)
        return self

    def push(self, rec: Any) -> None:
        """Append one StepRecord (object or dict) as a compact record."""
        if not self.enabled:
            return
        d = rec.to_dict() if hasattr(rec, "to_dict") else dict(rec)
        compact = {k: d.get(k) for k in STEP_STREAM_FIELDS}
        with self._lock:
            self._seq += 1
            compact["seq"] = self._seq
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1  # oldest unshipped record falls off
            self._ring.append(compact)

    def unacked(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r for r in self._ring if r["seq"] > self._acked]

    def ack(self, through_seq: int) -> None:
        with self._lock:
            self._acked = max(self._acked, int(through_seq))
            while self._ring and self._ring[0]["seq"] <= self._acked:
                self._ring.popleft()

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._acked = 0
            self.dropped = 0


_step_stream = StepStream()


def get_step_stream() -> StepStream:
    return _step_stream


def configure_step_stream(enabled: bool = True,
                          maxlen: Optional[int] = None) -> StepStream:
    """``maxlen=None`` leaves the ring size untouched — a disable call
    must not silently shrink a sized ring and drop buffered unshipped
    records."""
    return _step_stream.configure(enabled=enabled, maxlen=maxlen)


# ---------------------------------------------------------------------------
# aux streams (ISSUE 15): other planes ride the same publish beat
# ---------------------------------------------------------------------------

#: kind -> stream object exposing ``enabled``/``stream_id``/``pending()
#: -> Optional[list]``/``mark_pushed(batch)`` — published under
#: ``telemetry/<kind>/<node>`` on every push_node_telemetry beat.  The
#: serving plane registers its request-record log here, so request
#: traces ship over the exact transport (and degraded-mode semantics)
#: the metrics rollup already proved out.
_aux_streams: Dict[str, Any] = {}


def register_aux_stream(kind: str, stream: Any) -> None:
    _aux_streams[str(kind)] = stream


def get_aux_stream(kind: str) -> Optional[Any]:
    return _aux_streams.get(str(kind))


def aux_stream_key(kind: str, node_id: str) -> str:
    return f"telemetry/{kind}/{node_id}"


# ---------------------------------------------------------------------------
# publish side
# ---------------------------------------------------------------------------

_push_lock = threading.Lock()
_push_seq = 0


def push_node_telemetry(client: Any, node_id: str) -> Optional[Dict[str, Any]]:
    """One publish beat: ship this process's registry snapshot (plus
    clock-sync status) and the step stream's unacked batch.  Returns the
    metrics doc shipped, or None when the hub is disabled (nothing to
    roll up).  Raises the client's ConnectionError family on a store
    outage — callers (the publisher tick) degrade and retry; the step
    batch stays unacked so the next healthy beat flushes it."""
    global _push_seq
    from . import get_telemetry
    from .clocksync import get_clock_sync

    tel = get_telemetry()
    if not tel.enabled:
        return None
    with _push_lock:
        _push_seq += 1
        seq = _push_seq
    doc = {"v": ROLLUP_SCHEMA_V, "node": str(node_id), "seq": seq,
           "stream": _step_stream.stream_id,
           "clock": get_clock_sync().status(),
           "snapshot": tel.registry.snapshot()}
    stream = _step_stream
    pending = stream.unacked() if stream.enabled else []
    # metrics first: even if the step set fails mid-outage, the fresher
    # snapshot is already worth having
    client.set(_metrics_key(node_id), doc)
    if pending:
        client.set(_steps_key(node_id),
                   {"v": ROLLUP_SCHEMA_V, "node": str(node_id),
                    "stream": stream.stream_id, "records": pending})
        # ack only after the set SUCCEEDED: an outage mid-push leaves
        # the batch buffered for the next healthy beat (exactly-once is
        # the consumer's seq dedup, at-least-once is this retry)
        stream.ack(pending[-1]["seq"])
    # aux streams (request records, …) ride the same beat: the
    # publication is the stream's full retention window, so the store
    # key always holds the recent history a reader can assemble from —
    # marked pushed only after the set SUCCEEDED (degraded beats retry)
    for kind, aux in sorted(_aux_streams.items()):
        try:
            batch = aux.pending() if getattr(aux, "enabled", False) \
                else None
        except Exception as e:
            debug_once(f"rollup/aux-{kind}",
                       f"aux stream {kind} pending() failed ({e!r})")
            continue
        if not batch:
            continue
        client.set(aux_stream_key(kind, node_id),
                   {"v": ROLLUP_SCHEMA_V, "node": str(node_id),
                    "stream": aux.stream_id,
                    "clock": get_clock_sync().status(),
                    "records": batch})
        aux.mark_pushed(batch)
    return doc


# ---------------------------------------------------------------------------
# rollup (consume side)
# ---------------------------------------------------------------------------

class MetricsRollup:
    """Rank 0's (or the operator's) live merged view of the gang."""

    def __init__(self, node_label: str = "node"):
        self.node_label = str(node_label)
        #: node -> {"doc": metrics doc, "ingest_mono": local monotonic}
        self._nodes: Dict[str, Dict[str, Any]] = {}
        #: node -> step-stream consumer state
        self._steps: Dict[str, Dict[str, Any]] = {}
        #: rollup_tick loads persisted step watermarks at most once
        self._watermarks_loaded = False
        #: rollup_tick cadence stamp (monotonic; 0 = never ticked)
        self._last_tick_mono = 0.0
        self._lock = threading.Lock()

    # -- ingest --------------------------------------------------------

    def ingest_metrics(self, node_id: str, doc: Dict[str, Any]) -> bool:
        """Adopt a node's published snapshot (absolute state — newest
        wins).  Returns True when the doc advanced the view."""
        if not isinstance(doc, dict) or "snapshot" not in doc:
            return False
        nid = str(node_id)
        with self._lock:
            prev = self._nodes.get(nid)
            if (prev is not None
                    and prev["doc"].get("stream") == doc.get("stream")
                    and int(prev["doc"].get("seq", 0))
                    >= int(doc.get("seq", 0))):
                return False  # stale or already-seen publication
            self._nodes[nid] = {"doc": doc,
                                "ingest_mono": time.monotonic()}
        return True

    def ingest_steps(self, node_id: str, doc: Dict[str, Any]
                     ) -> List[Dict[str, Any]]:
        """Fold a node's step batch in; returns only the NEW records
        (seq above the per-stream watermark) — a re-pushed batch after
        a store restart contributes nothing twice."""
        if not isinstance(doc, dict):
            return []
        nid = str(node_id)
        stream = doc.get("stream")
        records = [r for r in (doc.get("records") or [])
                   if isinstance(r, dict) and "seq" in r]
        with self._lock:
            st = self._steps.setdefault(
                nid, {"stream": stream, "last_seq": 0, "ewma_ms": 0.0,
                      "count": 0, "last": None})
            if st["stream"] != stream:
                # the node restarted (new process, new sequence space)
                st.update({"stream": stream, "last_seq": 0})
            fresh = [r for r in records
                     if int(r["seq"]) > int(st["last_seq"])]
            for r in sorted(fresh, key=lambda r: int(r["seq"])):
                st["last_seq"] = int(r["seq"])
                st["count"] += 1
                st["last"] = r
                ms = r.get("step_time_ms")
                if isinstance(ms, (int, float)) and ms == ms:
                    st["ewma_ms"] = (float(ms) if st["ewma_ms"] == 0.0
                                     else 0.9 * st["ewma_ms"]
                                     + 0.1 * float(ms))
        return [dict(r, node=nid) for r in fresh]

    # -- read side -----------------------------------------------------

    def node_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._nodes)

    def node_doc(self, node_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._nodes.get(str(node_id))
            return entry["doc"] if entry else None

    def _gauge_value(self, snap: Dict[str, Any], name: str
                     ) -> Optional[float]:
        g = (snap.get("gauges") or {}).get(name)
        return None if g is None else float(g.get("value", 0.0))

    def _counter_value(self, snap: Dict[str, Any], name: str
                       ) -> Optional[float]:
        c = (snap.get("counters") or {}).get(name)
        return None if c is None else float(c.get("value", 0.0))

    def rows(self, hb_view: Optional[Dict[str, Dict[str, Any]]] = None
             ) -> List[Dict[str, Any]]:
        """Per-node operator rows for ``telemetry top`` — everything the
        3am question needs, none of it from bundles."""
        hb_view = hb_view or {}
        out = []
        with self._lock:
            nodes = {n: dict(e) for n, e in self._nodes.items()}
            steps = {n: dict(s) for n, s in self._steps.items()}
        for nid in sorted(set(nodes) | set(hb_view)):
            entry = nodes.get(nid)
            doc = entry["doc"] if entry else {}
            snap = doc.get("snapshot") or {}
            st = steps.get(nid) or {}
            hb = hb_view.get(nid) or {}
            last = st.get("last") or {}
            step = last.get("step")
            if step is None:
                step = self._gauge_value(snap, "train/step")
            ewma = st.get("ewma_ms") or self._gauge_value(
                snap, "train/step_time_ms_last")
            row = {
                "node": nid,
                "v": doc.get("v"),
                "published": entry is not None,
                "step": step,
                "step_time_ewma_ms": ewma,
                "loss": last.get("loss"),
                "goodput": self._gauge_value(snap, "goodput/fraction"),
                "hbm_frac": self._gauge_value(snap, "memory/hbm_frac"),
                "comm_fraction": self._gauge_value(
                    snap, "anatomy/comm_fraction"),
                "overlap_hiding_frac": self._gauge_value(
                    snap, "anatomy/overlap_hiding_frac"),
                "underflow_frac": self._gauge_value(
                    snap, "numerics/underflow_frac"),
                "gate_entropy": self._gauge_value(
                    snap, "moe/gate_entropy"),
                "moe_drop_rate": self._gauge_value(
                    snap, "moe/drop_rate"),
                "steps_streamed": st.get("count", 0),
                "store_outages": self._counter_value(
                    snap, "elasticity/store_outages_total"),
                "store_degraded_s": self._counter_value(
                    snap, "elasticity/store_degraded_seconds_total"),
                "hb_age_s": hb.get("age_s"),
                "left": bool(hb.get("left")),
                "clock_offset_s": (doc.get("clock") or {}).get("offset_s"),
            }
            out.append(row)
        return out

    # -- merged Prometheus export --------------------------------------

    def prometheus_text(self) -> str:
        """ONE exposition document for the whole gang.  Construction
        rules (the no-collision guarantee): every sample the rollup
        emits carries the ``node`` label — node-local series are always
        ``name{...,node="<id>"}``, gang aggregates are always
        ``name{...,node="_cluster"}``, and a node id equal to the
        reserved value is remapped by :func:`node_label_value` — so no
        two distinct sources can ever render the same sample key."""
        with self._lock:
            docs = {n: e["doc"] for n, e in self._nodes.items()}
        counters: Dict[str, Dict[str, Any]] = {}
        gauges: Dict[str, Dict[str, Any]] = {}
        hists: Dict[str, Dict[str, Any]] = {}
        for nid in sorted(docs):
            snap = docs[nid].get("snapshot") or {}
            for name, m in (snap.get("counters") or {}).items():
                counters.setdefault(name, {"help": m.get("help", ""),
                                           "by_node": {}})
                counters[name]["by_node"][nid] = float(m.get("value", 0.0))
            for name, m in (snap.get("gauges") or {}).items():
                gauges.setdefault(name, {"help": m.get("help", ""),
                                         "by_node": {}})
                gauges[name]["by_node"][nid] = float(m.get("value", 0.0))
            for name, m in (snap.get("histograms") or {}).items():
                hists.setdefault(name, {"help": m.get("help", ""),
                                        "by_node": {}})
                hists[name]["by_node"][nid] = m

        lines: List[str] = []

        def label(nid: str, extra: Optional[Dict[str, Any]] = None) -> str:
            labels = dict(extra or {})
            labels[self.node_label] = node_label_value(nid)
            return format_labels(labels)

        def agg_label(extra: Optional[Dict[str, Any]] = None) -> str:
            labels = dict(extra or {})
            labels[self.node_label] = CLUSTER_NODE_LABEL
            return format_labels(labels)

        for name in sorted(counters):
            e = counters[name]
            base = prom_name(name)
            if e["help"]:
                lines.append(f"# HELP {base} {escape_help(e['help'])}")
            lines.append(f"# TYPE {base} counter")
            for nid in sorted(e["by_node"]):
                lines.append(f"{base}{label(nid)} {e['by_node'][nid]:g}")
            lines.append(f"{base}{agg_label()} "
                         f"{sum(e['by_node'].values()):g}")
        for name in sorted(gauges):
            e = gauges[name]
            base = prom_name(name)
            if e["help"]:
                lines.append(f"# HELP {base} {escape_help(e['help'])}")
            lines.append(f"# TYPE {base} gauge")
            for nid in sorted(e["by_node"]):
                lines.append(f"{base}{label(nid)} {e['by_node'][nid]:g}")
        for name in sorted(hists):
            e = hists[name]
            base = prom_name(name)
            if e["help"]:
                lines.append(f"# HELP {base} {escape_help(e['help'])}")
            lines.append(f"# TYPE {base} histogram")
            agg_counts: Optional[List[float]] = None
            agg_buckets: Optional[List[float]] = None
            agg_sum, agg_count, agg_ok = 0.0, 0, True
            for nid in sorted(e["by_node"]):
                h = e["by_node"][nid]
                buckets = list(h.get("buckets") or [])
                raw = list(h.get("counts") or [])
                cum = 0
                for ub, c in zip(buckets, raw):
                    cum += c
                    lines.append(
                        f"{base}_bucket{label(nid, {'le': repr(float(ub))})}"
                        f" {cum}")
                cum += raw[-1] if len(raw) > len(buckets) else 0
                lines.append(f"{base}_bucket{label(nid, {'le': '+Inf'})}"
                             f" {cum}")
                lines.append(f"{base}_sum{label(nid)} "
                             f"{float(h.get('sum', 0.0)):g}")
                lines.append(f"{base}_count{label(nid)} "
                             f"{int(h.get('count', 0))}")
                if agg_buckets is None:
                    agg_buckets, agg_counts = buckets, list(raw)
                elif agg_buckets == buckets and agg_counts is not None \
                        and len(raw) == len(agg_counts):
                    agg_counts = [a + b for a, b in zip(agg_counts, raw)]
                else:
                    agg_ok = False  # mismatched bucket bounds don't sum
                agg_sum += float(h.get("sum", 0.0))
                agg_count += int(h.get("count", 0))
            if agg_ok and agg_buckets is not None and agg_counts:
                cum = 0
                for ub, c in zip(agg_buckets, agg_counts):
                    cum += c
                    lines.append(
                        f"{base}_bucket"
                        f"{agg_label({'le': repr(float(ub))})} {cum}")
                cum += (agg_counts[-1]
                        if len(agg_counts) > len(agg_buckets) else 0)
                lines.append(f"{base}_bucket{agg_label({'le': '+Inf'})}"
                             f" {cum}")
                lines.append(f"{base}_sum{agg_label()} {agg_sum:g}")
                lines.append(f"{base}_count{agg_label()} {agg_count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            docs = {n: e["doc"] for n, e in self._nodes.items()}
            steps = {n: dict(s) for n, s in self._steps.items()}
        return {"v": ROLLUP_SCHEMA_V, "nodes": sorted(docs),
                "docs": docs, "steps": steps}

    def save(self, out_dir: str) -> Dict[str, str]:
        """Atomic merged exports under ``out_dir``:
        ``cluster_metrics.prom`` (the labeled exposition) and
        ``cluster_metrics.json`` (the raw per-node documents)."""
        os.makedirs(out_dir, exist_ok=True)
        out = {}
        for name, text in (
                ("cluster_metrics.prom", self.prometheus_text()),
                ("cluster_metrics.json",
                 json.dumps(self.to_json(), default=str, indent=2))):
            path = os.path.join(out_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(text)
            os.replace(tmp, path)
            out[name] = path
        return out

    # -- step-watermark persistence ------------------------------------

    def load_step_watermarks(self, path: str) -> bool:
        """Adopt persisted per-(node, stream) sequence watermarks.  The
        dedup watermark otherwise lives only in process memory, so a
        restarted rank-0 agent would re-ingest each peer's last
        published batch and append duplicates to the append-only
        ``cluster_steps.jsonl`` — loading the saved watermarks first
        keeps the flush-exactly-once contract across agent restarts."""
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return False
        with self._lock:
            for nid, st in (doc.get("streams") or {}).items():
                if nid not in self._steps and isinstance(st, dict):
                    self._steps[nid] = {
                        "stream": st.get("stream"),
                        "last_seq": int(st.get("last_seq", 0)),
                        "ewma_ms": 0.0, "count": 0, "last": None}
        return True

    def save_step_watermarks(self, path: str) -> None:
        with self._lock:
            doc = {"streams": {
                n: {"stream": s.get("stream"),
                    "last_seq": int(s.get("last_seq", 0))}
                for n, s in self._steps.items()}}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)

    # -- gauges (rank 0's registry) ------------------------------------

    def publish_gauges(self) -> Dict[str, float]:
        """Feed the existing cluster gauges from the rollup — the same
        names ``publish_straggler_stats`` fills from heartbeat payloads,
        now sourced from real per-process registries/streams (the two
        agree when both run; the rollup wins on detail)."""
        from . import get_telemetry

        tel = get_telemetry()
        stats: Dict[str, float] = {}
        with self._lock:
            docs = {n: e["doc"] for n, e in self._nodes.items()}
            steps = {n: dict(s) for n, s in self._steps.items()}
        snaps = {n: d.get("snapshot") or {} for n, d in docs.items()}
        node_steps = []
        for nid in snaps:
            st = steps.get(nid) or {}
            last = st.get("last") or {}
            s = last.get("step")
            if s is None:
                s = self._gauge_value(snaps[nid], "train/step")
            if s is not None:
                node_steps.append(float(s))
        if len(node_steps) >= 2:
            stats["step_skew"] = max(node_steps) - min(node_steps)
            tel.set_gauge("elastic/straggler_step_skew",
                          stats["step_skew"],
                          help="max-min per-host step index across the gang")
        ewmas = [float(steps[n]["ewma_ms"]) for n in steps
                 if steps[n].get("ewma_ms")]
        if len(ewmas) >= 2:
            med = sorted(ewmas)[len(ewmas) // 2]
            stats["ewma_ratio"] = max(ewmas) / max(med, 1e-9)
            tel.set_gauge(
                "elastic/straggler_ewma_ratio", stats["ewma_ratio"],
                help="slowest host step-time EWMA over the median host's")
        gps = [v for v in (self._gauge_value(s, "goodput/fraction")
                           for s in snaps.values()) if v is not None]
        if gps:
            stats["goodput_min"] = min(gps)
            stats["goodput_mean"] = sum(gps) / len(gps)
            tel.set_gauge("elastic/cluster_goodput_min",
                          stats["goodput_min"],
                          help="worst per-host rolling goodput fraction")
            tel.set_gauge("elastic/cluster_goodput_mean",
                          stats["goodput_mean"],
                          help="mean per-host rolling goodput fraction")
        hbms = [v for v in (self._gauge_value(s, "memory/hbm_frac")
                            for s in snaps.values()) if v is not None]
        if hbms:
            stats["hbm_max"] = max(hbms)
            tel.set_gauge("elastic/cluster_hbm_max", stats["hbm_max"],
                          help="fullest per-host HBM used fraction")
        tel.set_gauge("rollup/nodes", float(len(snaps)),
                      help="nodes with a live metrics publication in "
                           "the rollup")
        return stats


_rollup = MetricsRollup()


def get_rollup() -> MetricsRollup:
    """Rank 0's process-global rollup (the agent's heartbeat tick feeds
    it; ``telemetry top`` builds its own transient one instead)."""
    return _rollup


def reset_rollup() -> None:
    global _rollup
    _rollup = MetricsRollup()


# ---------------------------------------------------------------------------
# ticks (rank 0 / operator)
# ---------------------------------------------------------------------------

def ingest_from_store(rollup: MetricsRollup, client: Any,
                      peer_ids: List[str]
                      ) -> Tuple[bool, List[Dict[str, Any]]]:
    """Pull every peer's published telemetry documents into ``rollup``;
    returns ``(changed, fresh_steps)`` — whether any node's snapshot
    advanced, and the NEW step records across all nodes (node-stamped).
    Raises the client's ConnectionError family when the store is down —
    callers on heartbeat paths guard."""
    changed = False
    fresh: List[Dict[str, Any]] = []
    for pid in peer_ids:
        doc = client.get(_metrics_key(pid))
        if isinstance(doc, dict):
            changed = rollup.ingest_metrics(pid, doc) or changed
        sdoc = client.get(_steps_key(pid))
        if isinstance(sdoc, dict):
            fresh.extend(rollup.ingest_steps(pid, sdoc))
    return changed, fresh


def collect_rollup(client: Any, peer_ids: List[str]) -> MetricsRollup:
    """A transient rollup built straight from the store (``telemetry
    top``, the chaos acceptance) — no agent, no bundles."""
    rollup = MetricsRollup()
    ingest_from_store(rollup, client, peer_ids)
    return rollup


STEP_WATERMARKS_FILE = "cluster_steps.state.json"


def rollup_tick(client: Any, peer_ids: List[str],
                out_dir: Optional[str] = None,
                every_s: float = 2.0) -> Optional[MetricsRollup]:
    """Rank 0's heartbeat-loop beat: ingest every peer's publications
    into the process-global rollup, publish the cluster gauges, and
    (when ``out_dir`` is set) keep the merged exports fresh —
    ``cluster_metrics.prom``/``.json`` plus an append-only
    ``cluster_steps.jsonl`` of every streamed step record, node-stamped
    (the merged JSONL export).  Store-down beats return None (counted
    by the caller's degraded path).

    ``every_s`` cadence-gates the ingest: the heartbeat loop calls this
    every monitor tick (default 0.1 s), but peers only re-publish every
    ``metrics_push_every_s`` — re-reading 2 store keys per peer at
    10 Hz would just load the single-threaded store for nothing."""
    rollup = _rollup
    now = time.monotonic()
    if every_s > 0 and now - rollup._last_tick_mono < every_s:
        return rollup
    if out_dir and not rollup._watermarks_loaded:
        # a restarted rank 0 must not re-append the batches still
        # sitting in the store — adopt the persisted seq watermarks
        rollup.load_step_watermarks(
            os.path.join(out_dir, STEP_WATERMARKS_FILE))
        rollup._watermarks_loaded = True
    changed, fresh = ingest_from_store(rollup, client, peer_ids)
    # stamp only after a SUCCESSFUL ingest (a raised store error skips
    # this), so a degraded beat retries on the next healthy tick
    rollup._last_tick_mono = now
    rollup.publish_gauges()
    if out_dir and (changed or fresh):
        # write only when the view MOVED: the heartbeat loop calls this
        # every tick, the publish side only every metrics_push_every_s
        try:
            os.makedirs(out_dir, exist_ok=True)
            if fresh:
                with open(os.path.join(out_dir, "cluster_steps.jsonl"),
                          "a") as fh:
                    for r in fresh:
                        fh.write(json.dumps(r, default=str) + "\n")
                rollup.save_step_watermarks(
                    os.path.join(out_dir, STEP_WATERMARKS_FILE))
            rollup.save(out_dir)
        except OSError as e:
            logger.warning(f"rollup: merged export write failed: {e!r}")
    return rollup


# ---------------------------------------------------------------------------
# `telemetry top` rendering
# ---------------------------------------------------------------------------

def _fmt(v: Any, pattern: str = "{:g}", none: str = "-") -> str:
    if v is None:
        return none
    try:
        return pattern.format(v)
    except (ValueError, TypeError):
        return str(v)


def render_top(rollup: MetricsRollup,
               hb_view: Optional[Dict[str, Dict[str, Any]]] = None,
               store_info: Optional[Dict[str, Any]] = None,
               silent_after_s: float = 30.0) -> str:
    """The live cluster view as a fixed-width table."""
    rows = rollup.rows(hb_view)
    header = (f"{'NODE':<14} {'STEP':>8} {'STEP_MS':>9} {'GOODPUT':>8} "
              f"{'HBM%':>6} {'COMM%':>6} {'UFLOW%':>6} {'LOSS':>10} "
              f"{'HB_AGE':>7} {'OUTAGES':>8} {'STATE':<10}")
    lines = []
    if store_info:
        lines.append(
            f"store: {store_info.get('endpoint', '?')}  "
            f"gen {store_info.get('generation', '?')}  "
            f"round {store_info.get('round', '?')}  "
            f"nodes {len(rows)}")
    lines.append(header)
    for r in rows:
        age = r.get("hb_age_s")
        if r.get("left"):
            state = "LEFT"
        elif (not r.get("published") and age is None) \
                or (age is not None and age > silent_after_s):
            state = "SILENT"
        else:
            state = "LIVE"
        hbm = r.get("hbm_frac")
        comm = r.get("comm_fraction")
        uflow = r.get("underflow_frac")
        lines.append(
            f"{r['node']:<14} {_fmt(r.get('step'), '{:.0f}'):>8} "
            f"{_fmt(r.get('step_time_ewma_ms'), '{:.1f}'):>9} "
            f"{_fmt(r.get('goodput'), '{:.3f}'):>8} "
            f"{_fmt(None if hbm is None else hbm * 100.0, '{:.1f}'):>6} "
            f"{_fmt(None if comm is None else comm * 100.0, '{:.1f}'):>6} "
            f"{_fmt(None if uflow is None else uflow * 100.0, '{:.1f}'):>6} "
            f"{_fmt(r.get('loss'), '{:.5g}'):>10} "
            f"{_fmt(age, '{:.1f}'):>7} "
            f"{_fmt(r.get('store_outages'), '{:.0f}'):>8} "
            f"{state:<10}")
    return "\n".join(lines)
