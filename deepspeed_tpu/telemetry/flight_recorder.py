"""Flight recorder — bounded black-box buffers + crash/debug bundles.

PR 1 gave the runtime passive telemetry (spans, metrics, StepRecords);
all of it evaporates with the process when a run dies.  This module is
the black box: it keeps bounded rings of the most recent StepRecords,
HealthEvents, and free-form annotations, and on demand — or on fatal
signal, unhandled exception, or watchdog trip — writes a self-contained
**debug bundle** an operator can read post-mortem:

* ``bundle.json``  — manifest: reason, recent StepRecords/HealthEvents/
  annotations, comms-logger summaries, a Prometheus snapshot of the
  metrics registry, and every registered context provider (e.g. the
  elastic agent's per-peer heartbeat ages, so a hang dump distinguishes
  "my host stalled" from "a peer died").
* ``trace.json``   — the span tracer's Chrome-trace slice (last-N host
  spans), loadable in Perfetto next to the XLA device lanes.
* ``env_report.json`` — the ``ds_report`` environment snapshot
  (versions, devices, native-op toolchain probes).
* ``stacks.txt``   — a faulthandler dump of EVERY thread's Python stack
  at dump time — for a hang, this is usually the answer.

The recorder is a process-global singleton (like the telemetry hub) so
the engine, the watchdog, the elastic agent, and ``bench.py``'s crash
path all feed one black box.  Recording is cheap (deque appends under a
lock); all the expensive work happens at dump time.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import signal
import socket
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from ..utils.logging import logger

BUNDLE_MANIFEST = "bundle.json"
BUNDLE_TRACE = "trace.json"
BUNDLE_ENV = "env_report.json"
BUNDLE_STACKS = "stacks.txt"
#: OOM forensics side file (telemetry/memory/oom.py) — present when the
#: bundle was dumped for a recognized device OOM
BUNDLE_MEMORY = "memory.json"


def _jsonable(obj: Any) -> Any:
    """Best-effort conversion for manifest payloads (numpy scalars, etc.)."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return str(obj)


class FlightRecorder:
    """Bounded in-memory black box with on-demand bundle dumps."""

    def __init__(self, max_records: int = 256,
                 output_path: str = "debug_bundles", retain: int = 5):
        self.max_records = int(max_records)
        self.output_path = output_path
        #: keep only the newest N bundle dirs under ``output_path`` —
        #: a watchdog stuck in trip/re-arm cycles must not fill the disk.
        #: <= 0 disables pruning.
        self.retain = int(retain)
        self._steps: "collections.deque" = collections.deque(
            maxlen=self.max_records)
        self._health: "collections.deque" = collections.deque(
            maxlen=self.max_records)
        self._annotations: "collections.deque" = collections.deque(
            maxlen=self.max_records)
        #: name -> zero-arg callable returning JSON-able context, invoked
        #: at DUMP time (providers see the state at failure, not at
        #: registration); failures are captured per provider, never fatal
        self._context_providers: Dict[str, Callable[[], Any]] = {}
        # REENTRANT: the fatal-signal handler runs dump() on the main
        # thread, possibly interrupting a record_* call that already
        # holds this lock — a plain Lock would deadlock the teardown
        # path the recorder exists to serve
        self._lock = threading.RLock()
        self._seq = 0
        self._installed = False
        self._prev_excepthook = None
        self._prev_signal_handlers: Dict[int, Any] = {}
        self.last_bundle_path: Optional[str] = None

    def configure(self, max_records: Optional[int] = None,
                  output_path: Optional[str] = None,
                  retain: Optional[int] = None) -> "FlightRecorder":
        with self._lock:
            if output_path:
                self.output_path = output_path
            if retain is not None:
                self.retain = int(retain)
            if max_records and int(max_records) != self.max_records:
                self.max_records = int(max_records)
                for name in ("_steps", "_health", "_annotations"):
                    setattr(self, name, collections.deque(
                        getattr(self, name), maxlen=self.max_records))
        return self

    def reset(self) -> None:
        """Test isolation: drop ring contents, context providers, and the
        last-bundle pointer (configuration and installed hooks stay)."""
        with self._lock:
            self._steps.clear()
            self._health.clear()
            self._annotations.clear()
            self._context_providers = {}
            self.last_bundle_path = None

    # -- recording (hot-ish path: deque append under a lock) ---------------

    def record_step(self, rec: Any) -> None:
        """Append a StepRecord (anything with ``to_dict()`` or a dict)."""
        d = rec.to_dict() if hasattr(rec, "to_dict") else dict(rec)
        with self._lock:
            self._steps.append(d)

    def record_health(self, event: Any) -> None:
        d = event.to_dict() if hasattr(event, "to_dict") else dict(event)
        with self._lock:
            self._health.append(d)

    def annotate(self, kind: str, payload: Dict[str, Any]) -> None:
        """Free-form breadcrumb (rendezvous joins, watchdog resets, ...)."""
        with self._lock:
            self._annotations.append(
                {"ts": time.time(), "kind": kind,
                 **{k: _jsonable(v) for k, v in payload.items()}})

    def register_context(self, name: str, fn: Callable[[], Any]) -> None:
        """Attach a named provider whose return value is embedded in every
        future bundle under ``context[name]`` (evaluated at dump time)."""
        with self._lock:
            self._context_providers[name] = fn

    def unregister_context(self, name: str) -> None:
        """Remove a provider added with :meth:`register_context` (no-op
        if absent).  Providers are strong references — a provider bound
        to an object with a shorter lifetime than the recorder (e.g. a
        bench-scoped serving front-end) must unregister to be
        collectable."""
        with self._lock:
            self._context_providers.pop(name, None)

    # -- dump --------------------------------------------------------------

    def _comm_snapshot(self) -> Dict[str, Any]:
        try:
            from ..comm.comm import comms_logger

            out: Dict[str, Any] = {
                "summary": {k: dict(v)
                            for k, v in comms_logger.summary().items()},
                "total_bytes": comms_logger.total_bytes(),
                "total_ops": comms_logger.total_ops(),
            }
            if comms_logger.exec_counts:
                out["exec_summary"] = {
                    k: dict(v)
                    for k, v in comms_logger.exec_summary().items()}
            return out
        except Exception as e:
            return {"error": repr(e)}

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None
             ) -> str:
        """Write a bundle directory and return its path.  Never raises on
        a partially-failing section — a crash handler calling this must
        get whatever CAN be written."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            steps = list(self._steps)
            health = list(self._health)
            annotations = list(self._annotations)
            providers = dict(self._context_providers)
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        bundle_dir = os.path.join(self.output_path,
                                  f"bundle-{stamp}-{seq:03d}")
        os.makedirs(bundle_dir, exist_ok=True)

        context: Dict[str, Any] = {}
        for name, fn in providers.items():
            try:
                context[name] = _jsonable(fn())
            except Exception as e:  # a dead provider must not kill the dump
                context[name] = {"error": repr(e)}

        from . import get_telemetry

        hub = get_telemetry()
        manifest: Dict[str, Any] = {
            "reason": reason,
            "ts": time.time(),
            "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "argv": list(sys.argv),
            "steps": steps,
            "health_events": health,
            "annotations": annotations,
            "comm": self._comm_snapshot(),
            "context": context,
            "extra": {k: _jsonable(v) for k, v in (extra or {}).items()},
            "files": [BUNDLE_TRACE, BUNDLE_ENV, BUNDLE_STACKS],
        }
        try:
            # store-clock mapping (telemetry/clocksync.py): the manifest
            # twin of the trace metadata, so archive tooling can reason
            # about alignment without parsing trace.json
            manifest["clock_sync"] = hub.tracer.clock_sync()
        except Exception as e:
            manifest["clock_sync"] = {"error": repr(e)}
        try:
            manifest["metrics_prom"] = hub.registry.prometheus_text()
        except Exception as e:
            manifest["metrics_prom"] = f"unavailable: {e!r}"
        try:
            with open(os.path.join(bundle_dir, BUNDLE_MANIFEST), "w") as fh:
                json.dump(manifest, fh, indent=2, default=str)
        except Exception as e:
            logger.error(f"flight recorder: manifest write failed: {e!r}")

        try:
            hub.tracer.save_chrome_trace(
                os.path.join(bundle_dir, BUNDLE_TRACE))
        except Exception as e:
            logger.warning(f"flight recorder: trace export failed: {e!r}")
        try:
            from ..env_report import collect as collect_env

            with open(os.path.join(bundle_dir, BUNDLE_ENV), "w") as fh:
                json.dump(collect_env(), fh, indent=2, default=str)
        except Exception as e:
            logger.warning(f"flight recorder: env report failed: {e!r}")
        try:
            with open(os.path.join(bundle_dir, BUNDLE_STACKS), "w") as fh:
                # every thread's Python stack — for a hang this is
                # usually the answer (which thread sits in which wait)
                faulthandler.dump_traceback(file=fh, all_threads=True)
        except Exception as e:
            logger.warning(f"flight recorder: stack dump failed: {e!r}")

        self.last_bundle_path = bundle_dir
        self._prune_bundles()
        logger.error(f"flight recorder: debug bundle written to "
                     f"{bundle_dir} ({reason})")
        return bundle_dir

    def _prune_bundles(self) -> None:
        """Retention: drop the oldest bundle dirs beyond ``retain`` —
        best-effort, a failed prune must never fail the dump."""
        if self.retain <= 0:
            return
        try:
            dirs = [os.path.join(self.output_path, d)
                    for d in os.listdir(self.output_path)
                    if d.startswith("bundle-")
                    and os.path.isdir(os.path.join(self.output_path, d))]
            # mtime with the (stamp, seq) name as tiebreak — several dumps
            # inside one mtime granule still prune oldest-first
            dirs.sort(key=lambda p: (os.path.getmtime(p),
                                     os.path.basename(p)))
            import shutil

            for stale in dirs[:-self.retain]:
                if stale == self.last_bundle_path:
                    continue
                shutil.rmtree(stale, ignore_errors=True)
        except OSError:
            pass

    # -- crash hooks -------------------------------------------------------

    def install(self, signals: bool = True, excepthook: bool = True) -> None:
        """Install the fatal-signal (SIGTERM/SIGABRT) and unhandled-
        exception hooks.  Idempotent; previous handlers are chained, so a
        launcher's own SIGTERM cleanup still runs after the dump."""
        if self._installed:
            return
        self._installed = True
        if excepthook:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
        if signals and threading.current_thread() is threading.main_thread():
            for signum in (signal.SIGTERM, signal.SIGABRT):
                try:
                    self._prev_signal_handlers[signum] = signal.signal(
                        signum, self._signal_handler)
                except (ValueError, OSError):  # not main thread / blocked
                    pass

    def uninstall(self) -> None:
        """Test isolation: restore the hooks install() replaced."""
        if not self._installed:
            return
        self._installed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        for signum, prev in self._prev_signal_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._prev_signal_handlers = {}

    def _excepthook(self, exc_type, exc, tb) -> None:
        # an exception that already carries a bundle (the engine's OOM
        # catch dumped one before re-raising HBMExhaustedError) must not
        # produce a near-identical duplicate here
        if getattr(exc, "ds_bundle_path", None):
            prev = self._prev_excepthook or sys.__excepthook__
            prev(exc_type, exc, tb)
            return
        try:
            self.dump(f"unhandled exception: {exc_type.__name__}: {exc}",
                      extra={"traceback": "".join(
                          traceback.format_exception(exc_type, exc, tb))})
        except Exception as e:  # the original exception must still print
            from ..utils.logging import debug_once

            debug_once("flight_recorder/excepthook_dump",
                       f"crash-bundle dump failed in excepthook ({e!r})")
        try:
            # OOM forensics (telemetry/memory): a RESOURCE_EXHAUSTED that
            # escaped the engine's own catch (placement, first compile,
            # user code) still gets memory.json next to the manifest
            from .memory.oom import augment_bundle_on_oom

            augment_bundle_on_oom(exc, self.last_bundle_path)
        except Exception as e:
            from ..utils.logging import debug_once

            debug_once("flight_recorder/oom_augment",
                       f"oom bundle augmentation failed ({e!r})")
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _signal_handler(self, signum, frame) -> None:
        try:
            self.dump(f"fatal signal {signal.Signals(signum).name}")
        except Exception as e:  # the signal's default action must proceed
            from ..utils.logging import debug_once

            debug_once("flight_recorder/signal_dump",
                       f"signal-bundle dump failed ({e!r})")
        prev = self._prev_signal_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_IGN:
            return  # the caller explicitly ignored this signal — honor it
        else:
            # restore the default disposition and re-raise so the process
            # still dies with the signal's semantics (exit code, core)
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)


def load_bundle(path: str) -> Dict[str, Any]:
    """Reload a dumped bundle: the manifest plus the side files (the
    round-trip the tests assert).  Missing side files load as ``None``."""
    with open(os.path.join(path, BUNDLE_MANIFEST)) as fh:
        out: Dict[str, Any] = {"manifest": json.load(fh)}
    for key, name, is_json in (("trace", BUNDLE_TRACE, True),
                               ("env_report", BUNDLE_ENV, True),
                               ("memory", BUNDLE_MEMORY, True),
                               ("stacks", BUNDLE_STACKS, False)):
        p = os.path.join(path, name)
        if not os.path.exists(p):
            out[key] = None
            continue
        with open(p) as fh:
            out[key] = json.load(fh) if is_json else fh.read()
    return out


_default = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _default


def configure_flight_recorder(max_records: Optional[int] = None,
                              output_path: Optional[str] = None,
                              retain: Optional[int] = None
                              ) -> FlightRecorder:
    return _default.configure(max_records=max_records,
                              output_path=output_path, retain=retain)


def recorder_from_config(tcfg: Any) -> Optional[FlightRecorder]:
    """Resolve the ``telemetry`` config group into the configured global
    recorder, or ``None`` when disabled — the ONE place the enable gate
    and default-bundle-path derivation live (entry.initialize and the
    engine both call this; duplicating it would drift)."""
    fr = tcfg.flight_recorder
    if not (fr.enabled and (tcfg.enabled or tcfg.watchdog.enabled)):
        return None
    rec = configure_flight_recorder(
        max_records=fr.max_records,
        output_path=fr.output_path or os.path.join(
            tcfg.output_path or "telemetry_logs", tcfg.job_name,
            "debug_bundles"),
        retain=fr.retain_bundles)
    # every bundle carries a memory snapshot (ISSUE 7 satellite): the
    # same numbers see_memory_usage prints, honoring the ledger and the
    # device-unresponsive latch — no separate enable gate needed
    from ..utils.memory import memory_status

    rec.register_context("memory_status", memory_status)
    return rec
