from .engine import DeepSpeedInferenceConfig, InferenceEngine, init_inference

__all__ = ["init_inference", "InferenceEngine", "DeepSpeedInferenceConfig"]
