"""RaggedInferenceEngineV2 — the FastGen-style serving engine.

Reference: ``deepspeed/inference/v2/engine_v2.py`` [K] —
``InferenceEngineV2.put(uids, tokens)`` over a ragged batch with blocked KV
cache and Dynamic SplitFuse scheduling (SURVEY §2.5 row "Inference v2").

TPU-first: instead of ragged kernels over dynamic shapes, the engine
compiles exactly TWO fixed-shape programs and reuses them for any request
mix (XLA traces once; raggedness lives in int32 metadata):

* ``prefill_chunk`` — ``chunk`` prompt tokens of ONE sequence, writing KV
  pages through the sequence's block table (Dynamic SplitFuse = long
  prompts become several chunk calls interleaved with decodes).
* ``decode_batch``  — one token for each of ``max_batch_slots`` sequences
  over the shared paged pool (``ops/pallas/paged_attention.py`` kernel).

Both donate the pool, so KV updates are in-place in HBM.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.llama import _rms_norm, _rope
from ...ops.pallas.paged_attention import paged_decode_attention
from ...utils.logging import log_dist
from .kv_cache import KVCacheConfig, init_kv_pool
from .scheduler import RaggedScheduler, Request


class RaggedInferenceEngineV2:
    def __init__(self, model: Any, params: Any,
                 cache_config: Optional[KVCacheConfig] = None,
                 max_batch_slots: int = 8, prefill_chunk: int = 128):
        self.model = model
        self.config = model.config
        self.params = params
        self.cache_config = cache_config or KVCacheConfig()
        if prefill_chunk % self.cache_config.block_size:
            raise ValueError("prefill_chunk must be a multiple of block_size")
        #: Mistral-style window, threaded into both compiled programs'
        #: masks (pages before the window still occupy pool slots — a
        #: window-aware page-release policy is a later optimization)
        self.window = getattr(self.config, "sliding_window", None)
        if self.cache_config.max_seq_len % prefill_chunk:
            # keeps every chunk's page-table slice in range: dynamic_slice
            # clamps out-of-bounds starts, which would silently retarget a
            # chunk's KV writes onto the sequence's EARLIER pages
            raise ValueError("max_seq_len must be a multiple of prefill_chunk")
        self.scheduler = RaggedScheduler(self.cache_config, max_batch_slots,
                                         prefill_chunk)
        self.pool = init_kv_pool(self.config, self.cache_config)
        self.max_slots = max_batch_slots
        self.chunk = prefill_chunk
        self._prefill = jax.jit(self._prefill_chunk_fn, donate_argnums=(1,))
        self._decode = jax.jit(self._decode_batch_fn, donate_argnums=(1,))
        log_dist(f"inference v2: pool={self.cache_config.num_blocks}"
                 f"x{self.cache_config.block_size} tokens, "
                 f"slots={max_batch_slots}, chunk={prefill_chunk}")

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------

    def _prefill_chunk_fn(self, params, pool, tokens, table_row, start_pos,
                          last_idx):
        """One chunk of one sequence: ``tokens [C]`` at positions
        ``start_pos + [0..C)``; returns (logits[V] at ``last_idx``, pool)."""
        c = self.config
        C = tokens.shape[0]
        bs = self.cache_config.block_size
        mb = self.cache_config.max_blocks_per_seq
        n_rep = c.num_heads // c.num_kv_heads
        positions = start_pos + jnp.arange(C)  # [C]
        x = jnp.take(params["embed"].astype(c.dtype), tokens, axis=0)  # [C,H]
        page_cursor = start_pos // bs  # chunk & start are page-aligned

        def layer(carry, xs):
            x, = carry
            lp, k_pool_l, v_pool_l = xs
            h = _rms_norm(x, lp["attn_norm"].astype(c.dtype), c.rms_norm_eps)
            q = jnp.einsum("sH,Hhd->shd", h, lp["attn"]["wq"].astype(c.dtype))
            kk = jnp.einsum("sH,Hhd->shd", h, lp["attn"]["wk"].astype(c.dtype))
            vv = jnp.einsum("sH,Hhd->shd", h, lp["attn"]["wv"].astype(c.dtype))
            q = _rope(q, positions, c.rope_theta)
            kk = _rope(kk, positions, c.rope_theta)
            # write this chunk's pages through the block table
            pages = jax.lax.dynamic_slice(table_row, (page_cursor,),
                                          (C // bs,))
            k_pool_l = k_pool_l.at[pages].set(
                kk.reshape(C // bs, bs, c.num_kv_heads, c.hd))
            v_pool_l = v_pool_l.at[pages].set(
                vv.reshape(C // bs, bs, c.num_kv_heads, c.hd))
            # attend over everything this sequence owns (prefix + chunk,
            # causal by absolute position)
            kf = k_pool_l[table_row].reshape(mb * bs, c.num_kv_heads, c.hd)
            vf = v_pool_l[table_row].reshape(mb * bs, c.num_kv_heads, c.hd)
            if n_rep > 1:
                kf = jnp.repeat(kf, n_rep, axis=1)
                vf = jnp.repeat(vf, n_rep, axis=1)
            from ...ops.masks import local_attention_mask

            scale = 1.0 / np.sqrt(c.hd)
            s = jnp.einsum("qhd,khd->hqk", q, kf).astype(jnp.float32) * scale
            mask = local_attention_mask(positions, jnp.arange(mb * bs),
                                        causal=True, window=self.window)
            s = jnp.where(mask[None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(c.dtype)
            attn = jnp.einsum("hqk,khd->qhd", p, vf)
            out = jnp.einsum("qhd,hdH->qH", attn,
                             lp["attn"]["wo"].astype(c.dtype))
            x = x + out
            h = _rms_norm(x, lp["mlp_norm"].astype(c.dtype), c.rms_norm_eps)
            ffn_out, _ = self.model._ffn(h[None], lp)
            x = x + ffn_out[0]
            return (x,), (k_pool_l, v_pool_l)

        (x,), (ks, vs) = jax.lax.scan(
            layer, (x,), (params["layers"], pool["k"], pool["v"]))
        x = _rms_norm(x, params["final_norm"].astype(c.dtype), c.rms_norm_eps)
        last_h = jax.lax.dynamic_index_in_dim(x, last_idx, axis=0,
                                              keepdims=False)
        logits = jnp.einsum("H,HV->V", last_h,
                            self.model._head(params).astype(c.dtype))
        return logits.astype(jnp.float32), {"k": ks, "v": vs}

    def _decode_batch_fn(self, params, pool, tokens, kv_lens, tables):
        """One token per slot: ``tokens [B]`` write KV at ``kv_lens [B]``
        through ``tables [B, max_blocks]``; returns (logits [B, V], pool)."""
        c = self.config
        B = tokens.shape[0]
        bs = self.cache_config.block_size
        x = jnp.take(params["embed"].astype(c.dtype), tokens, axis=0)
        pos = kv_lens[:, None]  # [B, 1]
        page_ids = tables[jnp.arange(B), kv_lens // bs]  # [B]
        offsets = kv_lens % bs

        def layer(carry, xs):
            x, = carry
            lp, k_pool_l, v_pool_l = xs
            h = _rms_norm(x, lp["attn_norm"].astype(c.dtype), c.rms_norm_eps)
            q = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wq"].astype(c.dtype))
            kk = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wk"].astype(c.dtype))
            vv = jnp.einsum("bH,Hhd->bhd", h, lp["attn"]["wv"].astype(c.dtype))
            q = _rope(q[:, None], pos, c.rope_theta)[:, 0]
            kk = _rope(kk[:, None], pos, c.rope_theta)[:, 0]
            k_pool_l = k_pool_l.at[page_ids, offsets].set(kk)
            v_pool_l = v_pool_l.at[page_ids, offsets].set(vv)
            attn = paged_decode_attention(q, k_pool_l, v_pool_l, tables,
                                          kv_lens + 1, window=self.window)
            out = jnp.einsum("bhd,hdH->bH", attn,
                             lp["attn"]["wo"].astype(c.dtype))
            x = x + out
            h = _rms_norm(x, lp["mlp_norm"].astype(c.dtype), c.rms_norm_eps)
            ffn_out, _ = self.model._ffn(h[:, None, :], lp)
            x = x + ffn_out[:, 0, :]
            return (x,), (k_pool_l, v_pool_l)

        (x,), (ks, vs) = jax.lax.scan(
            layer, (x,), (params["layers"], pool["k"], pool["v"]))
        x = _rms_norm(x, params["final_norm"].astype(c.dtype), c.rms_norm_eps)
        logits = jnp.einsum("bH,HV->bV", x,
                            self.model._head(params).astype(c.dtype))
        return logits.astype(jnp.float32), {"k": ks, "v": vs}

    # ------------------------------------------------------------------
    # serving surface
    # ------------------------------------------------------------------

    def put(self, prompt: List[int], max_new_tokens: int = 32) -> Request:
        """Admit one request (reference ``engine.put`` role)."""
        return self.scheduler.add_request(prompt, max_new_tokens)

    def _sample(self, logits: np.ndarray, temperature: float,
                rng: np.random.Generator) -> np.ndarray:
        if temperature <= 0:
            return np.argmax(logits, axis=-1)
        z = logits / temperature
        z = z - z.max(axis=-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=-1, keepdims=True)
        return np.array([rng.choice(p.shape[-1], p=row) for row in
                         np.atleast_2d(p)])

    def step(self, temperature: float = 0.0,
             eos_token_id: Optional[int] = None,
             rng: Optional[np.random.Generator] = None) -> int:
        """One scheduler step: at most one prefill chunk + one decode batch.
        Returns the number of tokens processed (SplitFuse keeps this near
        ``chunk + active_slots`` every step)."""
        rng = rng or np.random.default_rng(0)
        chunk, decode = self.scheduler.plan_step()
        n_tokens = 0
        if chunk is not None:
            req = chunk.request
            logits, self.pool = self._prefill(
                self.params, self.pool,
                jnp.asarray(chunk.tokens),
                jnp.asarray(self.scheduler.table_row(req)),
                jnp.int32(chunk.start_pos),
                jnp.int32(max(chunk.n_valid - 1, 0)))
            n_tokens += chunk.n_valid
            first = None
            if chunk.is_last:
                first = int(self._sample(np.asarray(logits)[None],
                                         temperature, rng)[0])
            self.scheduler.chunk_done(chunk, first, eos_token_id)
        if decode:
            B = self.max_slots
            tokens = np.zeros((B,), np.int32)
            kv_lens = np.zeros((B,), np.int32)
            tables = np.zeros((B, self.cache_config.max_blocks_per_seq),
                              np.int32)
            for req in decode:
                s = req.slot
                tokens[s] = req.generated[-1]
                kv_lens[s] = req.prefilled + len(req.generated) - 1
                tables[s] = self.scheduler.table_row(req)
            logits, self.pool = self._decode(
                self.params, self.pool, jnp.asarray(tokens),
                jnp.asarray(kv_lens), jnp.asarray(tables))
            logits = np.asarray(logits)
            sampled = self._sample(
                np.stack([logits[r.slot] for r in decode]), temperature, rng)
            self.scheduler.decode_done(decode, sampled, eos_token_id)
            n_tokens += len(decode)
        return n_tokens

    def generate(self, prompts: List[List[int]], max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0,
                 eos_token_id: Optional[int] = None,
                 ) -> List[List[int]]:
        """Drive the scheduler to completion over a ragged prompt batch.
        Returns the generated-token lists in prompt order."""
        rng = np.random.default_rng(seed)
        reqs = [self.put(p, max_new_tokens) for p in prompts]
        t0 = time.perf_counter()
        total = 0
        while self.scheduler.has_work:
            total += self.step(temperature, eos_token_id, rng)
        dt = time.perf_counter() - t0
        self.last_throughput = total / dt if dt > 0 else 0.0
        return [r.generated for r in reqs]


def build_engine_v2(model: Any, params: Any = None,
                    cache_config: Optional[KVCacheConfig] = None,
                    max_batch_slots: int = 8,
                    prefill_chunk: int = 128) -> RaggedInferenceEngineV2:
    if params is None:
        params = model.init_params(jax.random.PRNGKey(0))
    return RaggedInferenceEngineV2(model, params, cache_config,
                                   max_batch_slots, prefill_chunk)
