"""RaggedInferenceEngineV2 — the FastGen-style serving engine.

Reference: ``deepspeed/inference/v2/engine_v2.py`` [K] —
``InferenceEngineV2.put(uids, tokens)`` over a ragged batch with blocked KV
cache and Dynamic SplitFuse scheduling (SURVEY §2.5 row "Inference v2").

TPU-first: instead of ragged kernels over dynamic shapes, the engine
compiles a small number of fixed-shape programs and reuses them for any
request mix (XLA traces once; raggedness lives in int32 metadata):

* ``prefill_batch`` — ``chunk`` prompt tokens for each of up to
  ``prefill_batch`` sequences at once, writing KV pages through each row's
  block table (Dynamic SplitFuse = long prompts become several chunk calls
  interleaved with decodes; round 3 batches the chunks across sequences).
* ``decode_burst``  — ``k`` successive decode steps for all
  ``max_batch_slots`` sequences in ONE device program: sampling happens
  in-graph (greedy or temperature) and only ``[k, B]`` int32 token ids
  return to the host — no per-token logits round-trip over the tunnel.
  Page tables are fully reserved at admission (prompt + generation budget),
  so a burst never needs host page allocation mid-flight.

Architecture deltas (norms, positions, FFN, head) live in
``adapters.ModelAdapterV2`` — llama/mistral/mixtral AND OPT serve on the
same engine (reference keeps per-arch model implementations under
``inference/v2/model_implementations`` [K]).

Both programs donate the pool, so KV updates are in-place in HBM.

Prefill cost is O(pages allocated so far), not O(max_seq_len): each
chunk call gathers/masks only ``kb`` pages per row, where ``kb`` is the
smallest power-of-two page bucket covering the batch's deepest
``start_pos + chunk`` (VERDICT r3 item 6 — the round-2 "O(max_seq_len)
per chunk" cost note is gone).  Buckets are static shapes, so at most
``log2(max_blocks/chunk_blocks)+1`` prefill programs ever compile.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.pallas.paged_attention import paged_decode_attention
from ...telemetry.perf import get_compile_tracker, tracked_jit
from ...utils.logging import log_dist
from .adapters import ModelAdapterV2, make_adapter
from .kv_cache import KVCacheConfig, init_kv_pool
from .scheduler import RaggedScheduler, Request


class _null_ctx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return None


def _sample(logits: jnp.ndarray, temperature: jnp.ndarray,
            key: jax.Array) -> jnp.ndarray:
    """In-graph sampling over ``[N, V]`` fp32 logits: greedy when
    ``temperature <= 0``, else softmax sampling at that temperature."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    drawn = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, drawn, greedy)


class RaggedInferenceEngineV2:
    def __init__(self, model: Any, params: Any,
                 cache_config: Optional[KVCacheConfig] = None,
                 max_batch_slots: int = 8, prefill_chunk: int = 128,
                 prefill_batch: int = 2, decode_burst: int = 8,
                 adapter: Optional[ModelAdapterV2] = None,
                 mesh: Any = None,
                 scheduler_factory: Optional[Callable] = None,
                 ledger_key: str = "inference_v2/kv_pool",
                 moe_telemetry: bool = True):
        self.model = model
        self.adapter = adapter or make_adapter(model)
        self.config = model.config
        self.params = params
        self.cache_config = cache_config or KVCacheConfig()
        #: TP-sharded serving (reference v2 serves TP-sharded models):
        #: params land in their ``param_specs`` shardings, the KV pool is
        #: sharded on the kv-head dim over the ``tensor`` axis, and the
        #: compiled programs run under GSPMD.  Decode attention runs the
        #: PAGED PALLAS KERNEL per TP shard through an explicit shard_map
        #: over the kv-head axis (paged_decode_attention_tp) — heads are
        #: independent, so no cross-rank communication.
        self.mesh = mesh
        self.last_attn_path = None  # set at trace time by attend_fn
        self._tp = int(mesh.shape.get("tensor", 1)) if mesh is not None else 1
        if self._tp > 1 and self.adapter.kv_heads % self._tp:
            raise ValueError(
                f"tensor axis {self._tp} must divide kv heads "
                f"{self.adapter.kv_heads} for TP serving")
        if prefill_chunk % self.cache_config.block_size:
            raise ValueError("prefill_chunk must be a multiple of block_size")
        #: Mistral-style window, threaded into both compiled programs'
        #: masks (pages before the window still occupy pool slots — a
        #: window-aware page-release policy is a later optimization)
        self.window = self.adapter.window
        if self.cache_config.max_seq_len % prefill_chunk:
            # keeps every chunk's page-table slice in range: dynamic_slice
            # clamps out-of-bounds starts, which would silently retarget a
            # chunk's KV writes onto the sequence's EARLIER pages
            raise ValueError("max_seq_len must be a multiple of prefill_chunk")
        #: the serving plane swaps in its prefix-sharing scheduler here —
        #: same planner surface, refcounted page reservations
        make_sched = scheduler_factory or RaggedScheduler
        self.scheduler = make_sched(self.cache_config, max_batch_slots,
                                    prefill_chunk, prefill_batch)
        if self._tp > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            from ...parallel.mesh import strip_manual_axes

            spec_tree = self.model.param_specs(params)
            self.params = jax.tree.map(
                lambda p, s: jax.device_put(
                    p, NamedSharding(mesh, strip_manual_axes(*s))),
                params, spec_tree)
            # allocate the pool DIRECTLY into its sharding — a serving
            # config sizes the pool near HBM capacity, so transiently
            # materializing it replicated would OOM at startup
            pool_sharding = NamedSharding(
                mesh, PartitionSpec(None, None, None, "tensor", None))
            ad, cc = self.adapter, self.cache_config
            self.pool = tracked_jit(
                lambda: init_kv_pool(ad, cc), "inference_v2/pool_init",
                tracker=get_compile_tracker(),
                out_shardings={"k": pool_sharding, "v": pool_sharding})()
        else:
            self.pool = init_kv_pool(self.adapter, self.cache_config)
        from ...telemetry.memory import get_memory_ledger

        _mem = get_memory_ledger()
        if _mem.enabled:
            # the paged KV pool is the serving plane's dominant HBM
            # allocation — register it so `mem show` and OOM forensics
            # name it instead of reporting one giant untracked array
            # ledger_key is per-instance so multi-replica serving gets
            # DISTINCT kv_cache sub-keys (same key would silently replace)
            _mem.register_tree(
                "kv_cache", ledger_key, self.pool,
                tag=f"paged KV pool ({self.cache_config.num_blocks} x "
                    f"{self.cache_config.block_size} tokens)")
        self.max_slots = max_batch_slots
        self.chunk = prefill_chunk
        self.prefill_batch = max(1, prefill_batch)
        self.decode_burst = max(1, decode_burst)
        self._prefill = tracked_jit(self._prefill_batch_fn,
                                    "inference_v2/prefill",
                                    tracker=get_compile_tracker(),
                                    donate_argnums=(1,),
                                    static_argnames=("kb",))
        self._decode_jits: Dict[int, Callable] = {}
        self._key = jax.random.PRNGKey(0)
        #: MoE serving telemetry (ISSUE 19): when the model routes through
        #: a MOELayer, the decode program additionally returns the gate's
        #: per-expert load so the router/autoscaler can see hot experts.
        #: One persistent moe-only collector is active at trace time; the
        #: stats ride the program's output pytree ([L, E] load fractions
        #: averaged over the burst), so cached calls pay one tiny extra
        #: device→host transfer and zero recompiles.
        from ...telemetry import numerics

        self._moe_coll = (
            numerics.Collector(probes=False, moe=True, tag="serving")
            if moe_telemetry
            and getattr(model, "_moe_layer", None) is not None else None)
        #: host-side rolling per-expert load (fractions, sum≈1) and the
        #: derived max/mean imbalance — the router's placement signal
        self.last_moe_stats: Optional[Dict[str, Any]] = None
        log_dist(f"inference v2: pool={self.cache_config.num_blocks}"
                 f"x{self.cache_config.block_size} tokens, "
                 f"slots={max_batch_slots}, chunk={prefill_chunk}"
                 f"x{prefill_batch}, burst={self.decode_burst}, "
                 f"adapter={type(self.adapter).__name__}")

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------

    def _layer_step(self, lp, k_pool_l, v_pool_l, x_flat, positions_flat,
                    write_fn, attend_fn):
        """Shared per-layer skeleton: qkv → KV write → attention →
        post-attn block.  ``write_fn``/``attend_fn`` differ between the
        prefill and decode programs."""
        ad = self.adapter
        q, kk, vv = ad.qkv(lp, x_flat, positions_flat)
        k_pool_l, v_pool_l = write_fn(k_pool_l, v_pool_l, kk, vv)
        attn = attend_fn(q, k_pool_l, v_pool_l)
        x_flat = ad.post_attn(lp, x_flat, attn)
        return x_flat, k_pool_l, v_pool_l

    def _prefill_batch_fn(self, params, pool, tokens, tables, start_pos,
                          last_idx, temperature, key, *, kb):
        """Up to ``Bp`` sequences' chunks at once: ``tokens [Bp, C]`` at
        positions ``start_pos[r] + [0..C)``; rows beyond the live chunk
        count carry all-zero tables (page 0 = scratch).  ``kb`` (static)
        is the page bucket this program attends over — the first ``kb``
        pages of each row's table cover every key written so far, so the
        gather/mask is O(allocated), not O(max_seq_len).  Returns
        (sampled token ids ``[Bp]``, pool)."""
        ad = self.adapter
        Bp, C = tokens.shape
        bs = self.cache_config.block_size
        mb = int(kb)  # attend over the bucket, not the full table width
        n_rep = ad.num_heads // ad.kv_heads
        positions = start_pos[:, None] + jnp.arange(C)[None, :]  # [Bp, C]
        pos_flat = positions.reshape(-1)
        x = ad.embed(params, tokens.reshape(-1), pos_flat)  # [Bp*C, H]
        page_cursor = start_pos // bs  # chunks & starts are page-aligned

        # per-row page slice for this chunk's writes: [Bp, C//bs]
        pages = jax.vmap(
            lambda row, cur: jax.lax.dynamic_slice(row, (cur,), (C // bs,))
        )(tables, page_cursor)
        pages_flat = pages.reshape(-1)

        from ...ops.masks import local_attention_mask

        karange = jnp.arange(mb * bs)
        mask = jax.vmap(lambda p: local_attention_mask(
            p, karange, causal=True, window=self.window))(positions)
        mask = mask[:, None]  # [Bp, 1(head), C, mb*bs]

        def write_fn(k_pool_l, v_pool_l, kk, vv):
            k_pool_l = k_pool_l.at[pages_flat].set(
                kk.reshape(Bp * (C // bs), bs, ad.kv_heads, ad.head_dim))
            v_pool_l = v_pool_l.at[pages_flat].set(
                vv.reshape(Bp * (C // bs), bs, ad.kv_heads, ad.head_dim))
            return k_pool_l, v_pool_l

        def attend_fn(q, k_pool_l, v_pool_l):
            # gather only the bucket's pages (every key written so far
            # lives in the first kb pages of each row's table) and attend
            # chunk-queries over them — O(allocated), not O(max_seq_len)
            kf = k_pool_l[tables[:, :mb]].reshape(Bp, mb * bs, ad.kv_heads,
                                                  ad.head_dim)
            vf = v_pool_l[tables[:, :mb]].reshape(Bp, mb * bs, ad.kv_heads,
                                                  ad.head_dim)
            if n_rep > 1:
                kf = jnp.repeat(kf, n_rep, axis=2)
                vf = jnp.repeat(vf, n_rep, axis=2)
            qb = q.reshape(Bp, C, ad.num_heads, ad.head_dim)
            scale = 1.0 / np.sqrt(ad.head_dim)
            s = jnp.einsum("bqhd,bkhd->bhqk", qb, kf
                           ).astype(jnp.float32) * scale
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(ad.dtype)
            attn = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
            return attn.reshape(Bp * C, ad.num_heads, ad.head_dim)

        def layer(carry, xs):
            x, = carry
            lp, k_pool_l, v_pool_l = xs
            x, k_pool_l, v_pool_l = self._layer_step(
                lp, k_pool_l, v_pool_l, x, pos_flat, write_fn, attend_fn)
            return (x,), (k_pool_l, v_pool_l)

        (x,), (ks, vs) = jax.lax.scan(
            layer, (x,), (ad.layers(params), pool["k"], pool["v"]))
        x = ad.finalize(params, x).reshape(Bp, C, -1)
        last_h = jnp.take_along_axis(
            x, last_idx[:, None, None], axis=1)[:, 0]  # [Bp, H]
        logits = ad.logits(params, last_h)  # [Bp, V]
        return _sample(logits, temperature, key), {"k": ks, "v": vs}

    def _decode_burst_fn(self, params, pool, tokens, kv_lens, tables,
                         max_pos, temperature, key, *, n_steps: int):
        """``n_steps`` decode iterations entirely on device: each step
        writes KV at ``kv_lens`` through ``tables``, attends via the paged
        kernel, samples the next token in-graph and feeds it back.  Write
        positions clamp at ``max_pos`` (a slot that hit EOS/budget inside
        the burst only scribbles its own reserved pages; the host discards
        its surplus tokens).  Returns (token ids ``[n_steps, B]``, pool,
        moe gate stats dict or None)."""
        from ...telemetry import numerics

        ad = self.adapter
        B = tokens.shape[0]
        bs = self.cache_config.block_size

        def one_step(carry, key):
            tokens, kv_lens, pool = carry
            step_mark = numerics.scan_mark()
            wp = jnp.minimum(kv_lens, max_pos)  # [B] write positions
            page_ids = tables[jnp.arange(B), wp // bs]
            offsets = wp % bs
            x = ad.embed(params, tokens, wp)

            def write_fn(k_pool_l, v_pool_l, kk, vv):
                return (k_pool_l.at[page_ids, offsets].set(kk),
                        v_pool_l.at[page_ids, offsets].set(vv))

            def attend_fn(q, k_pool_l, v_pool_l):
                if self._tp > 1:
                    # the Pallas kernel runs PER TP SHARD via an explicit
                    # shard_map over the kv-head axis (heads independent,
                    # zero cross-rank comm) — no more einsum fallback
                    from ...ops.pallas.paged_attention import (
                        paged_decode_attention_tp)

                    self.last_attn_path = "pallas_tp_shard_map"
                    return paged_decode_attention_tp(
                        q, k_pool_l, v_pool_l, tables, wp + 1,
                        mesh=self.mesh, window=self.window)
                self.last_attn_path = "pallas"
                return paged_decode_attention(q, k_pool_l, v_pool_l, tables,
                                              wp + 1, window=self.window)

            def layer(carry, xs):
                x, = carry
                lp, k_pool_l, v_pool_l = xs
                mark = numerics.scan_mark()
                x, k_pool_l, v_pool_l = self._layer_step(
                    lp, k_pool_l, v_pool_l, x, wp, write_fn, attend_fn)
                # MoE gate stats (moe_stats inside model._ffn) must exit
                # the layer scan as ys — names ride the dict keys
                stats = numerics.scan_drain(mark)
                return (x,), (k_pool_l, v_pool_l, stats)

            (x,), (ks, vs, stats) = jax.lax.scan(
                layer, (x,), (ad.layers(params), pool["k"], pool["v"]))
            numerics.scan_collect(stats)  # keep the per-layer axis
            x = ad.finalize(params, x)
            logits = ad.logits(params, x)  # [B, V]
            nxt = _sample(logits, temperature, key)
            step_stats = numerics.scan_drain(step_mark)
            return (nxt, kv_lens + 1, {"k": ks, "v": vs}), (nxt, step_stats)

        keys = jax.random.split(key, n_steps)
        (_, _, pool), (toks, stats) = jax.lax.scan(
            one_step, (tokens, kv_lens, pool), keys)
        numerics.scan_collect(stats, combine=True)  # mean over the burst
        coll = numerics.active()
        moe_aux = coll.harvest() if coll is not None else None
        return toks, pool, moe_aux

    def _decode(self, n_steps: int) -> Callable:
        fn = self._decode_jits.get(n_steps)
        if fn is None:
            fn = tracked_jit(functools.partial(self._decode_burst_fn,
                                               n_steps=n_steps),
                             "inference_v2/decode_burst",
                             tracker=get_compile_tracker(),
                             static_context={"n_steps": n_steps},
                             donate_argnums=(1,))
            self._decode_jits[n_steps] = fn
        return fn

    # ------------------------------------------------------------------
    # serving surface
    # ------------------------------------------------------------------

    def put(self, prompt: List[int], max_new_tokens: int = 32) -> Request:
        """Admit one request (reference ``engine.put`` role)."""
        return self.scheduler.add_request(prompt, max_new_tokens)

    # -- MoE serving telemetry -----------------------------------------

    def _ingest_moe_stats(self, moe_aux: Dict[str, Any], tel: Any) -> None:
        """Host-side decode of the burst's gate stats: per-expert load
        gauges + the imbalance/drop scalars the router and autoscaler
        read.  Telemetry must never kill a decode step."""
        from ...telemetry import numerics

        try:
            decoded = numerics.decode(moe_aux)
            summary = numerics.summarize(decoded)
        except Exception:  # pragma: no cover - defensive
            return
        load = np.asarray(decoded.get("moe", {}).get("load", []),
                          dtype=np.float64)
        if load.ndim > 1:  # [L, E] → mean over the layer axis
            load = load.reshape(-1, load.shape[-1]).mean(axis=0)
        stats = {
            "load": load.tolist(),
            "imbalance": float(summary.get("moe_load_imbalance", 0.0)),
            "drop_rate": float(summary.get("moe_drop_rate", 0.0)),
        }
        self.last_moe_stats = stats
        if not tel.enabled:
            return
        for e, frac in enumerate(stats["load"]):
            tel.set_gauge(f"inference/moe/expert_load_e{e}", float(frac),
                          help="per-expert token-load fraction of the "
                               "last decode burst (hot-expert signal)")
        tel.set_gauge("inference/moe/load_imbalance", stats["imbalance"],
                      help="max/mean expert load of the last decode "
                           "burst (1.0 = balanced router)")
        tel.set_gauge("inference/moe/drop_rate", stats["drop_rate"],
                      help="capacity-dropped token fraction of the last "
                           "decode burst")

    def moe_load_imbalance(self) -> float:
        """Router-facing hot-expert signal: max/mean expert load of the
        last decode burst (1.0 = balanced; 0.0 = no MoE data yet)."""
        if not self.last_moe_stats:
            return 0.0
        return float(self.last_moe_stats.get("imbalance", 0.0))

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def _prefill_bucket(self, chunks) -> int:
        """Static page-bucket for this prefill call: smallest power-of-two
        multiple of the chunk's page count that covers the deepest row's
        ``start_pos + chunk`` keys.  Bounded program count (log2 buckets),
        O(allocated) gather cost."""
        bs = self.cache_config.block_size
        mb = self.cache_config.max_blocks_per_seq
        need = max((ch.start_pos + self.chunk) // bs for ch in chunks)
        kb = max(self.chunk // bs, 1)
        while kb < need:
            kb *= 2
        return min(kb, mb)

    def step(self, temperature: float = 0.0,
             eos_token_id: Optional[int] = None,
             rng: Optional[np.random.Generator] = None) -> int:
        """One scheduler step: a batched prefill call and/or a decode
        burst.  While prefill work exists the burst length is 1 so
        SplitFuse keeps interleaving chunks with decodes; once all prompts
        are in, decodes run ``decode_burst`` steps per dispatch.  Returns
        the number of tokens processed."""
        del rng  # sampling is in-graph now; kept for API compat
        from ...telemetry import get_telemetry

        tel = get_telemetry()
        chunks, decode = self.scheduler.plan_step()
        temp = jnp.float32(temperature)
        n_tokens = 0
        if chunks:
            Bp, C = self.prefill_batch, self.chunk
            tokens = np.zeros((Bp, C), np.int32)
            tables = np.zeros((Bp, self.cache_config.max_blocks_per_seq),
                              np.int32)
            start = np.zeros((Bp,), np.int32)
            last = np.zeros((Bp,), np.int32)
            for i, ch in enumerate(chunks):
                tokens[i] = ch.tokens
                tables[i] = self.scheduler.table_row(ch.request)
                start[i] = ch.start_pos
                last[i] = max(ch.n_valid - 1, 0)
            with tel.span("inference/prefill",
                          args={"chunks": len(chunks)}):
                sampled, self.pool = self._prefill(
                    self.params, self.pool, jnp.asarray(tokens),
                    jnp.asarray(tables), jnp.asarray(start),
                    jnp.asarray(last), temp, self._next_key(),
                    kb=self._prefill_bucket(chunks))
                sampled = np.asarray(sampled)
            for i, ch in enumerate(chunks):
                first = int(sampled[i]) if ch.is_last else None
                self.scheduler.chunk_done(ch, first, eos_token_id)
                n_tokens += ch.n_valid
            tel.inc_counter("inference/prefill_tokens", v=n_tokens,
                            help="prompt tokens written through prefill")
        if decode:
            # exactly TWO decode program shapes ever compile (1 and
            # decode_burst): over-running a request's budget inside a
            # burst is safe (max_pos clamps writes, the host discards
            # surplus tokens), so the tail reuses the full-length program
            burst = 1 if (chunks or self.scheduler.prefilling) \
                else self.decode_burst
            B = self.max_slots
            tokens = np.zeros((B,), np.int32)
            kv_lens = np.zeros((B,), np.int32)
            max_pos = np.zeros((B,), np.int32)
            tables = np.zeros((B, self.cache_config.max_blocks_per_seq),
                              np.int32)
            for req in decode:
                s = req.slot
                tokens[s] = req.generated[-1]
                kv_lens[s] = req.prefilled + len(req.generated) - 1
                max_pos[s] = len(req.prompt) + req.max_new_tokens - 1
                tables[s] = self.scheduler.table_row(req)
            from ...telemetry import numerics

            with tel.span("inference/decode_burst",
                          args={"burst": burst, "batch": len(decode)}):
                # the collector only matters at trace time (first call per
                # burst length) — cached calls just return the stats the
                # traced program already threads out
                with numerics.collecting(self._moe_coll) \
                        if self._moe_coll is not None else _null_ctx():
                    toks, self.pool, moe_aux = self._decode(burst)(
                        self.params, self.pool, jnp.asarray(tokens),
                        jnp.asarray(kv_lens), jnp.asarray(tables),
                        jnp.asarray(max_pos), temp, self._next_key())
                toks = np.asarray(toks)  # [burst, B]
            if moe_aux:
                self._ingest_moe_stats(moe_aux, tel)
            accepted = self.scheduler.decode_burst_done(decode, toks,
                                                        eos_token_id)
            n_tokens += accepted
            tel.inc_counter("inference/decode_tokens", v=accepted,
                            help="decode tokens accepted by the scheduler")
        return n_tokens

    def generate(self, prompts: List[List[int]], max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0,
                 eos_token_id: Optional[int] = None,
                 ) -> List[List[int]]:
        """Drive the scheduler to completion over a ragged prompt batch.
        Returns the generated-token lists in prompt order."""
        self._key = jax.random.PRNGKey(seed)
        reqs = [self.put(p, max_new_tokens) for p in prompts]
        t0 = time.perf_counter()
        total = 0
        while self.scheduler.has_work:
            total += self.step(temperature, eos_token_id)
        dt = time.perf_counter() - t0
        self.last_throughput = total / dt if dt > 0 else 0.0
        from ...telemetry import get_telemetry

        get_telemetry().set_gauge(
            "inference/tokens_per_sec", self.last_throughput,
            help="tokens/sec of the last generate() drive")
        return [r.generated for r in reqs]


def build_engine_v2(model: Any, params: Any = None,
                    cache_config: Optional[KVCacheConfig] = None,
                    max_batch_slots: int = 8,
                    prefill_chunk: int = 128,
                    prefill_batch: int = 2,
                    decode_burst: int = 8,
                    mesh: Any = None,
                    scheduler_factory: Optional[Callable] = None,
                    ledger_key: str = "inference_v2/kv_pool"
                    ) -> RaggedInferenceEngineV2:
    if params is None:
        params = model.init_params(jax.random.PRNGKey(0))
    return RaggedInferenceEngineV2(model, params, cache_config,
                                   max_batch_slots, prefill_chunk,
                                   prefill_batch, decode_burst,
                                   mesh=mesh, scheduler_factory=scheduler_factory,
                                   ledger_key=ledger_key)
