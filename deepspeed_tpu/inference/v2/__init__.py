"""Inference v2 — FastGen-style ragged serving (SURVEY §2.5 "Inference v2").

Reference: ``deepspeed/inference/v2/`` [K] — ragged/continuous batching,
Dynamic SplitFuse scheduling, blocked KV cache.  TPU-first re-design:
static-shape compiled programs (one chunked-prefill, one batched-decode)
reused every scheduler step, with raggedness carried by a paged KV pool +
block tables instead of dynamic shapes.
"""

from .engine_v2 import RaggedInferenceEngineV2, build_engine_v2
from .kv_cache import BlockAllocator, KVCacheConfig, init_kv_pool
from .scheduler import Request, RequestState, RaggedScheduler

__all__ = [
    "RaggedInferenceEngineV2", "build_engine_v2",
    "BlockAllocator", "KVCacheConfig", "init_kv_pool",
    "Request", "RequestState", "RaggedScheduler",
]
