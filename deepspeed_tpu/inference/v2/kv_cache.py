"""Blocked (paged) KV cache — pool + block allocator.

Reference: ``deepspeed/inference/v2/ragged/`` [K] — ``BlockedKVCache`` /
``KVCacheManager``: KV memory is a pool of fixed-size pages shared by all
sequences; each sequence owns a list of page ids (the block table), so HBM
is committed in page units as sequences grow instead of a padded
``[B, max_len]`` rectangle up front.

TPU-first: the pool is ONE device array per K/V with the layer dim stacked
(``[L, num_blocks, block_size, kv_h, d]``) so the per-layer ``lax.scan``
in the decode program slices it like every other stacked-layer tensor;
page bookkeeping (free list, tables) is plain host Python — it never
enters the compiled program, which only ever sees int32 table arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    num_blocks: int = 256          # pool pages (page 0 reserved as scratch)
    block_size: int = 16           # tokens per page
    max_seq_len: int = 2048        # per-sequence logical capacity

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_seq_len // self.block_size)


def init_kv_pool(model_or_adapter: Any, cache_config: KVCacheConfig
                 ) -> Dict[str, jnp.ndarray]:
    """Zeroed pool sized from the model's (layers, kv-heads, head-dim).
    Accepts either a ``ModelAdapterV2`` (preferred — normalizes families
    without ``num_kv_heads``, e.g. OPT) or a raw model config."""
    c = model_or_adapter
    if hasattr(c, "kv_heads"):  # adapter protocol
        shape = (c.num_layers, cache_config.num_blocks,
                 cache_config.block_size, c.kv_heads, c.head_dim)
        return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}
    shape = (c.num_layers, cache_config.num_blocks, cache_config.block_size,
             c.num_kv_heads, c.hd)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


class BlockAllocator:
    """Free-list page allocator.  Page 0 is reserved: inactive batch slots
    point their whole table at it, so clamped kernel lookups always resolve
    to a valid page and dead slots scribble only on scratch."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (page 0 is reserved)")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        #: O(1) membership for the double-free check — the free list grew
        #: past linear-scan sizes once serving workloads started churning
        #: pages through the prefix cache
        self._free_set = set(self._free)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise MemoryError(f"KV pool exhausted: want {n} pages, "
                              f"{len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def check_owned(self, b: int) -> None:
        """Raise a descriptive error unless ``b`` is a currently-allocated
        page id.  The serving plane's refcounting is built on this
        invariant — a silent bad free there would corrupt a *shared*
        prefix page that other requests are still reading."""
        if not 0 < b < self.num_blocks:
            raise ValueError(
                f"free of out-of-range page id {b!r}: valid ids are "
                f"1..{self.num_blocks - 1} (page 0 is the reserved scratch "
                f"page and is never allocated or freed)")
        if b in self._free_set:
            raise ValueError(
                f"double free of page {b}: it is already on the free list "
                f"({len(self._free)} pages free of {self.num_blocks - 1}) — "
                f"the caller freed a block table twice or freed a table it "
                f"does not own")

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            self.check_owned(b)
            self._free.append(b)
            self._free_set.add(b)
