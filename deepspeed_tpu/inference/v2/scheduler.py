"""Ragged request scheduler — continuous batching + chunked prefill.

Reference: ``deepspeed/inference/v2/ragged/ragged_manager.py`` +
``scheduling_utils`` [K] and the Dynamic SplitFuse policy (FastGen,
arXiv 2401.08671 [P]): long prompts are split into fixed-size chunks and
prefill work is interleaved with running decodes so every forward pass
carries a near-constant token count — which on TPU is exactly what keeps
ONE compiled program shape serving an arbitrary request mix.

Host-side only: states, block tables and the free list live in Python;
the device sees fixed-shape int32 arrays each step.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Deque, List, Optional

import numpy as np

from .kv_cache import BlockAllocator, KVCacheConfig


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    RUNNING = "running"
    DONE = "done"


@dataclasses.dataclass
class Request:
    uid: int
    prompt: List[int]
    max_new_tokens: int
    state: RequestState = RequestState.WAITING
    generated: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    prefilled: int = 0          # prompt tokens already written to the pool
    slot: int = -1              # decode batch slot while RUNNING
    #: prefill-lattice priority (lower = sooner) — the serving plane maps
    #: latency classes here so an interactive prompt's chunks are not
    #: stuck behind a batch of background prefills; plain engine use
    #: leaves everything at 0 (pure FIFO)
    priority: int = 0

    @property
    def length(self) -> int:
        return self.prefilled + len(self.generated)

    @property
    def remaining_budget(self) -> int:
        """Generation tokens this request may still emit."""
        return max(self.max_new_tokens - len(self.generated), 0)

    def pages_needed(self, block_size: int) -> int:
        total = len(self.prompt) + self.max_new_tokens
        return -(-total // block_size)


@dataclasses.dataclass
class PrefillChunk:
    request: Request
    tokens: np.ndarray          # [chunk] int32, zero-padded
    start_pos: int              # first position this chunk covers
    n_valid: int                # true tokens in this chunk
    is_last: bool               # finishing chunk → sample first token


class RaggedScheduler:
    """Admission + step planning over a fixed decode-slot budget.

    Each :meth:`plan_step` returns at most one :class:`PrefillChunk` (the
    SplitFuse interleave unit) plus the current decode batch composition;
    the engine runs the corresponding compiled programs.
    """

    def __init__(self, cache_config: KVCacheConfig, max_batch_slots: int = 8,
                 prefill_chunk: int = 128, prefill_batch: int = 1):
        if prefill_chunk % cache_config.block_size:
            raise ValueError("prefill_chunk must be a multiple of block_size")
        self.cache = cache_config
        self.allocator = self._make_allocator(cache_config.num_blocks)
        self.chunk = prefill_chunk
        self.prefill_batch = max(1, prefill_batch)
        self.max_slots = max_batch_slots
        self.slots: List[Optional[Request]] = [None] * max_batch_slots
        self.waiting: Deque[Request] = deque()
        self.prefilling: Deque[Request] = deque()
        self._uid = 0

    def _make_allocator(self, num_blocks: int) -> BlockAllocator:
        """Subclass hook: the serving scheduler swaps in its refcounted
        allocator without constructing a discarded base one."""
        return BlockAllocator(num_blocks)

    # -- request surface ---------------------------------------------------

    def validate(self, prompt: List[int], max_new_tokens: int) -> None:
        """Reject malformed requests with an error naming the offending
        field.  The serving front-end forwards user input directly into
        this scheduler, so every invariant the planner relies on (a
        non-empty prompt, a positive generation budget, a pool that can
        ever hold the request) must be checked HERE, not discovered as a
        has_work spin or a zero-length chunk later."""
        if not prompt:
            raise ValueError("prompt: must be a non-empty token list")
        if max_new_tokens <= 0:
            raise ValueError(
                f"max_new_tokens: must be >= 1, got {max_new_tokens} "
                f"(a request that may generate nothing would occupy a "
                f"decode slot forever)")
        total = len(prompt) + max_new_tokens
        if total > self.cache.max_seq_len:
            raise ValueError(f"request of {total} tokens exceeds "
                             f"max_seq_len {self.cache.max_seq_len}")
        need = -(-total // self.cache.block_size)
        if need > self.cache.num_blocks - 1:  # page 0 reserved
            # reject now: _admit could never place it and generate() would
            # spin on has_work forever
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.cache.num_blocks - 1}")

    def add_request(self, prompt: List[int], max_new_tokens: int) -> Request:
        self.validate(prompt, max_new_tokens)
        req = Request(uid=self._uid, prompt=list(prompt),
                      max_new_tokens=max_new_tokens)
        self._uid += 1
        self.waiting.append(req)
        from ...telemetry import get_telemetry

        get_telemetry().inc_counter("inference/requests",
                                    help="requests admitted to the queue")
        return req

    @property
    def has_work(self) -> bool:
        return (bool(self.waiting) or bool(self.prefilling)
                or any(s is not None for s in self.slots))

    # -- planning ------------------------------------------------------------

    def _free_slot(self) -> int:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return -1

    def _reserve(self, req: Request) -> bool:
        """Reserve the request's full page budget; ``False`` defers
        admission.  Subclass hook: the serving scheduler overrides this
        to satisfy part of the reservation from shared prefix pages."""
        need = req.pages_needed(self.cache.block_size)
        if need > self.allocator.num_free:
            return False
        req.blocks = self.allocator.allocate(need)
        return True

    def _release(self, req: Request) -> None:
        """Return a finished/cancelled request's pages.  Subclass hook:
        the serving scheduler routes this through refcounts so shared
        prefix pages survive until their last holder lets go."""
        self.allocator.free(req.blocks)

    def _admit(self) -> None:
        """Move waiting → prefilling while a slot + enough pages exist.
        Pages for the FULL request (prompt + generation budget) are reserved
        at admission so a running sequence can never die of pool OOM
        mid-flight (the reference's conservative scheduling mode)."""
        while self.waiting:
            req = self.waiting[0]
            slot = self._free_slot()
            if slot < 0:
                return
            if not self._reserve(req):
                return
            self.waiting.popleft()
            req.state = RequestState.PREFILL
            req.slot = slot
            self.slots[slot] = req
            self.prefilling.append(req)

    def telemetry_gauges(self) -> dict:
        """Scheduler occupancy numbers, published each ``plan_step``:
        queue depth, decode-slot occupancy, and KV-pool utilization (the
        pool is the 'cache' — utilization is pages committed to live
        sequences over the allocatable pool)."""
        occupied = sum(1 for s in self.slots if s is not None)
        allocatable = self.cache.num_blocks - 1  # page 0 reserved
        return {
            "inference/queue_depth": float(len(self.waiting)),
            "inference/prefilling": float(len(self.prefilling)),
            "inference/batch_occupancy": occupied / max(self.max_slots, 1),
            "inference/kv_pool_utilization":
                (allocatable - self.allocator.num_free) / max(allocatable, 1),
        }

    def plan_step(self) -> tuple:
        """→ (list[PrefillChunk] (≤ ``prefill_batch``, one chunk per
        distinct prefilling request), decode_requests) for this step."""
        self._admit()
        from ...telemetry import get_telemetry

        tel = get_telemetry()
        if tel.enabled:
            for name, v in self.telemetry_gauges().items():
                tel.set_gauge(name, v)
        chunks: List[PrefillChunk] = []
        for req in list(self.prefilling)[:self.prefill_batch]:
            start = req.prefilled
            n_valid = min(self.chunk, len(req.prompt) - start)
            toks = np.zeros((self.chunk,), np.int32)
            toks[:n_valid] = req.prompt[start:start + n_valid]
            is_last = start + n_valid >= len(req.prompt)
            chunks.append(PrefillChunk(request=req, tokens=toks,
                                       start_pos=start, n_valid=n_valid,
                                       is_last=is_last))
        decode = [r for r in self.slots
                  if r is not None and r.state is RequestState.RUNNING]
        return chunks, decode

    # -- state transitions (called by the engine) ----------------------------

    def chunk_done(self, chunk: PrefillChunk, first_token: Optional[int],
                   eos_token_id: Optional[int] = None) -> None:
        req = chunk.request
        req.prefilled += chunk.n_valid
        if chunk.is_last:
            assert req.prefilled == len(req.prompt)
            self.prefilling.remove(req)
            req.state = RequestState.RUNNING
            if first_token is not None:
                req.generated.append(int(first_token))
                self._maybe_finish(req, int(first_token), eos_token_id)

    def decode_done(self, requests: List[Request], tokens: np.ndarray,
                    eos_token_id: Optional[int] = None) -> None:
        """Single-step acceptance — a burst of 1 (kept for callers that
        decode one token per dispatch)."""
        if not requests:
            return
        order = {r.slot: i for i, r in enumerate(requests)}
        row = np.zeros((1, max(order) + 1), tokens.dtype)
        for req in requests:
            row[0, req.slot] = tokens[order[req.slot]]
        self.decode_burst_done(requests, row, eos_token_id)

    def decode_burst_done(self, requests: List[Request], tokens: np.ndarray,
                          eos_token_id: Optional[int] = None) -> int:
        """Accept an in-graph burst's ``[n_steps, B]`` token matrix: each
        request takes its slot's column until it finishes (EOS/budget);
        surplus tokens a done slot generated inside the burst are
        discarded.  Returns the number of accepted tokens."""
        accepted = 0
        for req in requests:
            col = tokens[:, req.slot]
            for tok in col:
                if req.state is not RequestState.RUNNING:
                    break
                req.generated.append(int(tok))
                accepted += 1
                self._maybe_finish(req, int(tok), eos_token_id)
        return accepted

    def _maybe_finish(self, req: Request, tok: int,
                      eos: Optional[int]) -> None:
        if (len(req.generated) >= req.max_new_tokens
                or (eos is not None and tok == eos)):
            req.state = RequestState.DONE
            self._release(req)
            req.blocks = []
            if req.slot >= 0:
                self.slots[req.slot] = None
                req.slot = -1
            from ...telemetry import get_telemetry

            get_telemetry().inc_counter(
                "inference/requests_done",
                help="requests finished (EOS or budget)")

    def cancel(self, req: Request) -> None:
        """Abort a request in any pre-DONE state: pages come back, the
        slot frees, and the planner never sees it again.  The serving
        front-end's ``cancel`` verb lands here."""
        if req.state is RequestState.DONE:
            return
        if req in self.waiting:
            self.waiting.remove(req)
        if req in self.prefilling:
            self.prefilling.remove(req)
        if req.blocks:
            self._release(req)
            req.blocks = []
        if req.slot >= 0:
            self.slots[req.slot] = None
            req.slot = -1
        req.state = RequestState.DONE
        from ...telemetry import get_telemetry

        get_telemetry().inc_counter(
            "inference/requests_cancelled",
            help="requests aborted before completion")

    def table_row(self, req: Request) -> np.ndarray:
        row = np.zeros((self.cache.max_blocks_per_seq,), np.int32)
        row[:len(req.blocks)] = req.blocks
        return row
