"""Per-architecture model adapters for the v2 ragged serving engine.

Reference: ``deepspeed/inference/v2/model_implementations/`` [K] ships one
implementation per family (llama, mistral, mixtral, opt, ...) that plugs
into the shared ragged engine/KV machinery.  The TPU-native equivalent is
this small hook protocol: the engine owns paging, scheduling and the two
compiled programs; an adapter owns exactly the architecture deltas —
embedding (rotary vs learned positions), norm flavor (RMS vs LayerNorm),
QKV projection (biasless vs biased), and the FFN/residual block.

All hooks operate on FLAT token batches ``[N, ...]`` so the same adapter
serves both compiled programs (prefill rows are flattened ``[Bp*C]``,
decode is ``[B]``).  Positions come in as an ``[N]`` int32 vector.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp


def make_adapter(model: Any) -> "ModelAdapterV2":
    """Pick the adapter for a model instance (reference role:
    ``inference/v2``'s per-arch policy registry)."""
    name = type(model).__name__
    if name in _REGISTRY:
        return _REGISTRY[name](model)
    for cls_name, adapter_cls in _REGISTRY.items():
        if any(cls_name == base.__name__
               for base in type(model).__mro__):
            return adapter_cls(model)
    raise NotImplementedError(
        f"no v2 adapter for model class {name}; register one in "
        f"deepspeed_tpu.inference.v2.adapters._REGISTRY")


class ModelAdapterV2:
    """Architecture hooks consumed inside the engine's jitted programs."""

    def __init__(self, model: Any):
        self.model = model
        self.config = model.config

    # -- static shape facts -------------------------------------------------

    @property
    def num_layers(self) -> int:
        return self.config.num_layers

    @property
    def num_heads(self) -> int:
        return self.config.num_heads

    @property
    def kv_heads(self) -> int:
        return getattr(self.config, "num_kv_heads", self.config.num_heads)

    @property
    def head_dim(self) -> int:
        return self.config.hd

    @property
    def dtype(self) -> Any:
        return self.config.dtype

    @property
    def window(self) -> Optional[int]:
        return getattr(self.config, "sliding_window", None)

    # -- jit-side hooks -----------------------------------------------------

    def layers(self, params: Any) -> Any:
        """Stacked-layer pytree with leading ``L`` dim (for ``lax.scan``)."""
        return params["layers"]

    def embed(self, params: Any, tokens: jnp.ndarray,
              positions: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def qkv(self, lp: Any, x: jnp.ndarray, positions: jnp.ndarray
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """``x [N, H]`` → (q ``[N, h, d]``, k, v ``[N, kv_h, d]``) with any
        rotary encoding already applied."""
        raise NotImplementedError

    def post_attn(self, lp: Any, x: jnp.ndarray,
                  attn: jnp.ndarray) -> jnp.ndarray:
        """Output projection + residual + FFN block: ``x [N, H]``,
        ``attn [N, h, d]`` → ``[N, H]``."""
        raise NotImplementedError

    def finalize(self, params: Any, x: jnp.ndarray) -> jnp.ndarray:
        """Final norm over ``[N, H]``."""
        raise NotImplementedError

    def logits(self, params: Any, x: jnp.ndarray) -> jnp.ndarray:
        """LM head: ``[N, H]`` → fp32 ``[N, V]``."""
        raise NotImplementedError


class LlamaV2Adapter(ModelAdapterV2):
    """Llama/Mistral/Mixtral family: RoPE, RMSNorm, biasless projections.
    Mixtral routes through the same hooks because ``post_attn`` delegates the
    FFN to ``model._ffn`` (the MoE override)."""

    def embed(self, params, tokens, positions):
        del positions  # rotary — positions enter at qkv time
        return jnp.take(params["embed"].astype(self.dtype), tokens, axis=0)

    def qkv(self, lp, x, positions):
        from ...models.llama import _rms_norm, _rope

        c = self.config
        dt = self.dtype
        h = _rms_norm(x, lp["attn_norm"].astype(dt), c.rms_norm_eps)
        q = jnp.einsum("nH,Hhd->nhd", h, lp["attn"]["wq"].astype(dt))
        k = jnp.einsum("nH,Hhd->nhd", h, lp["attn"]["wk"].astype(dt))
        v = jnp.einsum("nH,Hhd->nhd", h, lp["attn"]["wv"].astype(dt))
        q = _rope(q, positions, c.rope_theta)
        k = _rope(k, positions, c.rope_theta)
        return q, k, v

    def post_attn(self, lp, x, attn):
        from ...models.llama import _rms_norm

        c = self.config
        dt = self.dtype
        out = jnp.einsum("nhd,hdH->nH", attn, lp["attn"]["wo"].astype(dt))
        x = x + out
        h = _rms_norm(x, lp["mlp_norm"].astype(dt), c.rms_norm_eps)
        ffn_out, _ = self.model._ffn(h[None], lp)
        return x + ffn_out[0]

    def finalize(self, params, x):
        from ...models.llama import _rms_norm

        c = self.config
        return _rms_norm(x, params["final_norm"].astype(self.dtype),
                         c.rms_norm_eps)

    def logits(self, params, x):
        head = self.model._head(params).astype(self.dtype)
        return jnp.einsum("nH,HV->nV", x, head).astype(jnp.float32)


class OPTV2Adapter(ModelAdapterV2):
    """OPT family: learned absolute positions (+2 offset), LayerNorm with
    bias, biased projections, ReLU MLP, tied head.  This is the family the
    llama-schema engine could not serve (VERDICT round 2, missing #5)."""

    def embed(self, params, tokens, positions):
        from ...models.opt import POSITION_OFFSET

        dt = self.dtype
        pos_idx = jnp.minimum(positions + POSITION_OFFSET,
                              params["pos_embed"].shape[0] - 1)
        return (jnp.take(params["embed"].astype(dt), tokens, axis=0)
                + jnp.take(params["pos_embed"].astype(dt), pos_idx, axis=0))

    def qkv(self, lp, x, positions):
        from ...models.bert import _layer_norm

        del positions  # learned positions were added at embed time
        c = self.config
        dt = self.dtype
        h = _layer_norm(x, lp["attn_ln_w"].astype(dt),
                        lp["attn_ln_b"].astype(dt), c.layer_norm_eps)
        a = lp["attn"]
        q = jnp.einsum("nH,Hhd->nhd", h, a["wq"].astype(dt)) \
            + a["bq"].astype(dt)
        k = jnp.einsum("nH,Hhd->nhd", h, a["wk"].astype(dt)) \
            + a["bk"].astype(dt)
        v = jnp.einsum("nH,Hhd->nhd", h, a["wv"].astype(dt)) \
            + a["bv"].astype(dt)
        return q, k, v

    def post_attn(self, lp, x, attn):
        from ...models.bert import _layer_norm

        c = self.config
        dt = self.dtype
        out = jnp.einsum("nhd,hdH->nH", attn, lp["attn"]["wo"].astype(dt)) \
            + lp["attn"]["bo"].astype(dt)
        x = x + out
        h = _layer_norm(x, lp["mlp_ln_w"].astype(dt),
                        lp["mlp_ln_b"].astype(dt), c.layer_norm_eps)
        h = jnp.maximum(h @ lp["mlp"]["w_in"].astype(dt)
                        + lp["mlp"]["b_in"].astype(dt), 0)
        return x + h @ lp["mlp"]["w_out"].astype(dt) \
            + lp["mlp"]["b_out"].astype(dt)

    def finalize(self, params, x):
        from ...models.bert import _layer_norm

        c = self.config
        return _layer_norm(x, params["final_ln_w"].astype(self.dtype),
                           params["final_ln_b"].astype(self.dtype),
                           c.layer_norm_eps)

    def logits(self, params, x):
        # tied head: logits against the input embedding table
        return jnp.einsum("nH,VH->nV",
                          x, params["embed"].astype(self.dtype)
                          ).astype(jnp.float32)


_REGISTRY = {
    "LlamaModel": LlamaV2Adapter,
    "MixtralModel": LlamaV2Adapter,
    "OPTModel": OPTV2Adapter,
}
