"""Inference engine v1 — ``init_inference`` + KV-cache generation.

Reference: ``deepspeed/inference/engine.py`` [K] —
``deepspeed.init_inference(model, tensor_parallel={"tp_size": N}, dtype,
replace_with_kernel_inject, max_out_tokens, ...) → InferenceEngine`` with
``.generate(...)`` and module-call passthrough (SURVEY §2.5, §3.6).

TPU-first: "kernel injection" IS the Pallas decode-attention kernel the
model's ``decode_step`` already calls; "AutoTP" IS the model's PartitionSpec
rules over the ``tensor`` mesh axis — so this engine only assembles mesh +
sharded params + jitted prefill/decode and runs the token loop.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel.mesh import MeshLayout
from ..telemetry.perf import get_compile_tracker, tracked_jit
from ..utils import groups as groups_mod
from ..utils.logging import log_dist


@dataclasses.dataclass
class DeepSpeedInferenceConfig:
    tensor_parallel: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"tp_size": 1})
    dtype: Any = jnp.bfloat16
    replace_with_kernel_inject: bool = True  # Pallas decode kernel
    max_out_tokens: int = 1024
    min_out_tokens: int = 1


class InferenceEngine:
    def __init__(self, model: Any, params: Any,
                 config: DeepSpeedInferenceConfig, mesh=None):
        self.module = model
        self.config = config
        tp = int(config.tensor_parallel.get("tp_size", 1))
        if mesh is None:
            layout = MeshLayout.infer(max(tp, 1), tp=tp, dp=1)
            mesh = groups_mod.initialize_mesh(layout)
        self.mesh = mesh
        if callable(getattr(model, "param_specs", None)) and tp > 1:
            specs = model.param_specs()
            shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), specs)
            params = jax.device_put(params, shardings)
        self.params = params
        self._prefill = tracked_jit(model.prefill, "inference/prefill",
                                    tracker=get_compile_tracker())
        self._decode = tracked_jit(model.decode_step, "inference/decode",
                                   tracker=get_compile_tracker())
        log_dist(f"init_inference: tp={tp} dtype={config.dtype} "
                 f"kernel_inject={config.replace_with_kernel_inject}")

    def __call__(self, input_ids: jnp.ndarray, **kwargs):
        """Module passthrough (reference engine forwards to the model)."""
        return self.module.forward(self.params, input_ids)

    def forward(self, input_ids: jnp.ndarray, **kwargs):
        return self(input_ids, **kwargs)

    def generate(self, input_ids: Any, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, eos_token_id: Optional[int] = None
                 ) -> jnp.ndarray:
        """Greedy (temperature=0) or sampled generation with a KV cache.
        ``input_ids [B, S]`` → ``[B, S + max_new_tokens]`` (right-padded with
        the last generated token after EOS)."""
        input_ids = jnp.asarray(input_ids)
        B, S = input_ids.shape
        max_len = S + max_new_tokens
        cache = self.module.init_cache(B, max_len)
        logits, cache = self._prefill(self.params, input_ids, cache)
        rng = jax.random.PRNGKey(seed)
        out = [input_ids]
        done = jnp.zeros((B,), bool)
        last = None
        for i in range(max_new_tokens):
            if temperature > 0:
                rng, sub = jax.random.split(rng)
                scaled = logits / temperature
                if top_k > 0:
                    kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
                    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
                tok = jax.random.categorical(sub, scaled)
            else:
                tok = jnp.argmax(logits, axis=-1)
            tok = tok.astype(jnp.int32)
            if eos_token_id is not None:
                tok = jnp.where(done & (last is not None),
                                last if last is not None else tok, tok)
                done = done | (tok == eos_token_id)
            out.append(tok[:, None])
            last = tok
            if eos_token_id is not None and bool(jnp.all(done)):
                pad = jnp.tile(tok[:, None], (1, max_new_tokens - i - 1))
                out.append(pad)
                break
            if i < max_new_tokens - 1:
                logits, cache = self._decode(self.params, cache, tok)
        return jnp.concatenate(out, axis=1)


def init_inference(model: Any = None, config: Any = None, model_params: Any = None,
                   tensor_parallel: Optional[Dict[str, Any]] = None,
                   dtype: Any = jnp.bfloat16, replace_with_kernel_inject: bool = True,
                   max_out_tokens: int = 1024, mesh=None,
                   **kwargs) -> InferenceEngine:
    """Reference call shape [L HF-DS:452 context]; ``model`` is one of our
    model objects, ``model_params`` its pytree (or taken from
    ``model.init_params`` when absent — tiny models/testing)."""
    if config is None:
        config = DeepSpeedInferenceConfig(
            tensor_parallel=tensor_parallel or {"tp_size": 1},
            dtype=dtype, replace_with_kernel_inject=replace_with_kernel_inject,
            max_out_tokens=max_out_tokens)
    elif isinstance(config, dict):
        config = DeepSpeedInferenceConfig(**config)
    if model_params is None:
        if not hasattr(model, "init_params"):
            raise ValueError("model_params required")
        model_params = model.init_params(jax.random.PRNGKey(0))
    return InferenceEngine(model, model_params, config, mesh=mesh)
