"""Autotuner — search (zero stage × micro-batch) by timing compiled steps.

Reference: ``deepspeed/autotuning/`` [K] — ``Autotuner`` +
``GridSearchTuner/RandomTuner/ModelBasedTuner`` launch short profiling jobs
over ``zero_optimization.stage`` / micro-batch / offload and pick the best
throughput config (SURVEY §2.5).

TPU-first: no subprocess launches — each candidate is one jit compile + a
few timed steps IN PROCESS (XLA gives OOM errors synchronously, and
compile+run of a candidate costs seconds, not a job launch).  The search
space and the emitted best-config JSON keep the reference's shape.
"""

from __future__ import annotations

import itertools
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from ..utils.logging import log_dist, logger

DEFAULT_TUNING_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8],
}

#: reference's offload dimension (enabled by passing this as tuning_space
#: or merging it in; kept out of the default so fast tunes stay fast)
OFFLOAD_TUNING_SPACE = {
    **DEFAULT_TUNING_SPACE,
    "zero_optimization.offload_optimizer.device": ["none", "cpu"],
}


def zero_memory_estimate(n_params: int, stage: int, dp: int,
                         offload_optimizer: bool = False,
                         dtype_bytes: int = 2) -> int:
    """Device bytes/chip for model+optimizer state under a ZeRO stage —
    the reference ModelBasedTuner's memory model (params bf16 2N, grads
    2N, fp32 master+Adam moments 12N, sharded per stage; activations not
    included — the XLA OOM check catches those)."""
    params = dtype_bytes * n_params
    grads = dtype_bytes * n_params
    opt = 12 * n_params  # fp32 master + m + v
    if offload_optimizer:
        opt = 0
    if stage >= 1:
        opt //= dp
    if stage >= 2:
        grads //= dp
    if stage >= 3:
        params //= dp
    return params + grads + opt


class Autotuner:
    def __init__(self, engine_factory: Callable[[Dict[str, Any]], Any],
                 batch_factory: Callable[[Dict[str, Any]], Any],
                 base_config: Dict[str, Any],
                 tuning_space: Optional[Dict[str, List[Any]]] = None,
                 metric: str = "throughput", warmup_steps: int = 1,
                 timed_steps: int = 3, model_params_count: int = 0,
                 hbm_bytes: int = 0, dp_size: int = 1):
        """``engine_factory(config_dict) -> engine`` builds a fresh engine;
        ``batch_factory(config_dict) -> batch`` supplies a matching global
        batch.  Factories own model/params so the tuner stays generic.

        ``model_params_count`` + ``hbm_bytes`` (both optional) switch on
        the memory model: candidates whose estimated state footprint
        exceeds HBM are pruned WITHOUT compiling them (the reference
        ModelBasedTuner's OOM pre-screen); 0 for either disables it."""
        self.engine_factory = engine_factory
        self.batch_factory = batch_factory
        self.base_config = base_config
        self.space = tuning_space or DEFAULT_TUNING_SPACE
        self.metric = metric
        self.warmup_steps = warmup_steps
        self.timed_steps = timed_steps
        self.model_params_count = int(model_params_count)
        self.hbm_bytes = int(hbm_bytes)
        self.dp_size = max(int(dp_size), 1)
        self.records: List[Dict[str, Any]] = []

    def _apply(self, cfg: Dict[str, Any], dotted: str, value: Any) -> None:
        node = cfg
        parts = dotted.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def _candidates(self):
        keys = list(self.space.keys())
        for combo in itertools.product(*(self.space[k] for k in keys)):
            cfg = json.loads(json.dumps(self.base_config))
            for k, v in zip(keys, combo):
                self._apply(cfg, k, v)
            yield dict(zip(keys, combo)), cfg

    def _memory_prune(self, combo: Dict[str, Any]) -> bool:
        """True → skip without compiling (estimated state exceeds HBM)."""
        if not (self.model_params_count and self.hbm_bytes):
            return False
        base_zero = self.base_config.get("zero_optimization", {})
        stage = int(combo.get("zero_optimization.stage",
                              base_zero.get("stage", 0)))
        base_off = base_zero.get("offload_optimizer", {}).get("device",
                                                              "none")
        offload = str(combo.get(
            "zero_optimization.offload_optimizer.device", base_off)) == "cpu"
        est = zero_memory_estimate(self.model_params_count, stage,
                                   self.dp_size, offload)
        return est > self.hbm_bytes

    def _measure(self, cfg: Dict[str, Any]) -> Optional[float]:
        try:
            engine = self.engine_factory(cfg)
            batch = self.batch_factory(cfg)

            def sync(metrics):
                # scalar fetch = real fence (block_until_ready is a no-op
                # on tunneled platforms)
                return float(metrics["loss"])

            m = None
            for _ in range(self.warmup_steps):
                m = engine.train_step(batch)
            if m is not None:  # warmup_steps=0 is legal
                sync(m)
            t0 = time.perf_counter()
            for _ in range(self.timed_steps):
                m = engine.train_step(batch)
            sync(m)
            dt = (time.perf_counter() - t0) / self.timed_steps
            samples = int(engine.train_batch_size or 1)
            return samples / dt
        except Exception as e:
            logger.warning(f"autotuning candidate failed: {e}")
            return None

    def tune(self) -> Dict[str, Any]:
        best, best_rate = None, -1.0
        for combo, cfg in self._candidates():
            if self._memory_prune(combo):
                self.records.append({"combo": combo, "throughput": None,
                                     "pruned": "memory_model"})
                log_dist(f"autotuning {combo} -> PRUNED (memory model)")
                continue
            rate = self._measure(cfg)
            rec = {"combo": combo, "throughput": rate}
            self.records.append(rec)
            log_dist(f"autotuning {combo} -> "
                     f"{'FAIL' if rate is None else f'{rate:.1f} samples/s'}")
            if rate is not None and rate > best_rate:
                best, best_rate = (combo, cfg), rate
        if best is None:
            raise RuntimeError("no autotuning candidate succeeded")
        combo, cfg = best
        log_dist(f"autotuning best: {combo} at {best_rate:.1f} samples/s")
        return {"best_config": cfg, "best_combo": combo,
                "throughput": best_rate, "records": self.records}

    def write_best(self, path: str) -> None:
        result = self.tune()
        with open(path, "w") as f:
            json.dump(result["best_config"], f, indent=2)


def autotune(engine_factory, batch_factory, base_config,
             tuning_space=None) -> Dict[str, Any]:
    return Autotuner(engine_factory, batch_factory, base_config,
                     tuning_space).tune()
