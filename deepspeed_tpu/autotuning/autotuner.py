"""Autotuner — search (zero stage × micro-batch) by timing compiled steps.

Reference: ``deepspeed/autotuning/`` [K] — ``Autotuner`` +
``GridSearchTuner/RandomTuner/ModelBasedTuner`` launch short profiling jobs
over ``zero_optimization.stage`` / micro-batch / offload and pick the best
throughput config (SURVEY §2.5).

TPU-first: no subprocess launches — each candidate is one jit compile + a
few timed steps IN PROCESS (XLA gives OOM errors synchronously, and
compile+run of a candidate costs seconds, not a job launch).  The search
space and the emitted best-config JSON keep the reference's shape.
"""

from __future__ import annotations

import itertools
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from ..utils.logging import log_dist, logger

DEFAULT_TUNING_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8],
}


class Autotuner:
    def __init__(self, engine_factory: Callable[[Dict[str, Any]], Any],
                 batch_factory: Callable[[Dict[str, Any]], Any],
                 base_config: Dict[str, Any],
                 tuning_space: Optional[Dict[str, List[Any]]] = None,
                 metric: str = "throughput", warmup_steps: int = 1,
                 timed_steps: int = 3):
        """``engine_factory(config_dict) -> engine`` builds a fresh engine;
        ``batch_factory(config_dict) -> batch`` supplies a matching global
        batch.  Factories own model/params so the tuner stays generic."""
        self.engine_factory = engine_factory
        self.batch_factory = batch_factory
        self.base_config = base_config
        self.space = tuning_space or DEFAULT_TUNING_SPACE
        self.metric = metric
        self.warmup_steps = warmup_steps
        self.timed_steps = timed_steps
        self.records: List[Dict[str, Any]] = []

    def _apply(self, cfg: Dict[str, Any], dotted: str, value: Any) -> None:
        node = cfg
        parts = dotted.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def _candidates(self):
        keys = list(self.space.keys())
        for combo in itertools.product(*(self.space[k] for k in keys)):
            cfg = json.loads(json.dumps(self.base_config))
            for k, v in zip(keys, combo):
                self._apply(cfg, k, v)
            yield dict(zip(keys, combo)), cfg

    def _measure(self, cfg: Dict[str, Any]) -> Optional[float]:
        try:
            engine = self.engine_factory(cfg)
            batch = self.batch_factory(cfg)
            for _ in range(self.warmup_steps):
                engine.train_step(batch)
            jax.block_until_ready(engine.state.params)
            t0 = time.perf_counter()
            for _ in range(self.timed_steps):
                engine.train_step(batch)
            jax.block_until_ready(engine.state.params)
            dt = (time.perf_counter() - t0) / self.timed_steps
            samples = int(engine.train_batch_size or 1)
            return samples / dt
        except Exception as e:
            logger.warning(f"autotuning candidate failed: {e}")
            return None

    def tune(self) -> Dict[str, Any]:
        best, best_rate = None, -1.0
        for combo, cfg in self._candidates():
            rate = self._measure(cfg)
            rec = {"combo": combo, "throughput": rate}
            self.records.append(rec)
            log_dist(f"autotuning {combo} -> "
                     f"{'FAIL' if rate is None else f'{rate:.1f} samples/s'}")
            if rate is not None and rate > best_rate:
                best, best_rate = (combo, cfg), rate
        if best is None:
            raise RuntimeError("no autotuning candidate succeeded")
        combo, cfg = best
        log_dist(f"autotuning best: {combo} at {best_rate:.1f} samples/s")
        return {"best_config": cfg, "best_combo": combo,
                "throughput": best_rate, "records": self.records}

    def write_best(self, path: str) -> None:
        result = self.tune()
        with open(path, "w") as f:
            json.dump(result["best_config"], f, indent=2)


def autotune(engine_factory, batch_factory, base_config,
             tuning_space=None) -> Dict[str, Any]:
    return Autotuner(engine_factory, batch_factory, base_config,
                     tuning_space).tune()
