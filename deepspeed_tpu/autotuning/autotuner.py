"""Autotuner — the reference API shape, now a shim over ``tuning/``.

Reference: ``deepspeed/autotuning/`` [K] — ``Autotuner`` +
``GridSearchTuner/RandomTuner/ModelBasedTuner`` launch short profiling jobs
over ``zero_optimization.stage`` / micro-batch / offload and pick the best
throughput config (SURVEY §2.5).

TPU-first: no subprocess launches — each candidate is one jit compile + a
few timed steps IN PROCESS.  Since ISSUE 9 the measurement itself lives in
the autotuning plane (``deepspeed_tpu/tuning/``): trials are DEVICE-FENCED
per timed step (the loss-scalar fetch is the fence — ``time.time()``
around unfenced dispatches measured host queueing on tunneled chips),
scored from the engine's own StepRecords when telemetry is on, and pruned
through the ledger-calibrated memory model.  This module keeps the
reference entry points (``Autotuner``/``ModelBasedTuner``/``autotune``,
the ``DS_AUTOTUNING_*`` env flows, the emitted best-config JSON shape) as
thin shims over that plane.
"""

from __future__ import annotations

import itertools
import json
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger

DEFAULT_TUNING_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8],
}

#: reference's offload dimension (enabled by passing this as tuning_space
#: or merging it in; kept out of the default so fast tunes stay fast)
OFFLOAD_TUNING_SPACE = {
    **DEFAULT_TUNING_SPACE,
    "zero_optimization.offload_optimizer.device": ["none", "cpu"],
}


def zero_memory_estimate(n_params: int, stage: int, dp: int,
                         offload_optimizer: bool = False,
                         dtype_bytes: int = 2) -> int:
    """Device bytes/chip for model+optimizer state under a ZeRO stage —
    the reference ModelBasedTuner's memory model (params bf16 2N, grads
    2N, fp32 master+Adam moments 12N, sharded per stage; activations not
    included — the XLA OOM check catches those)."""
    params = dtype_bytes * n_params
    grads = dtype_bytes * n_params
    opt = 12 * n_params  # fp32 master + m + v
    if offload_optimizer:
        opt = 0
    if stage >= 1:
        opt //= dp
    if stage >= 2:
        grads //= dp
    if stage >= 3:
        params //= dp
    return params + grads + opt


class Autotuner:
    def __init__(self, engine_factory: Callable[[Dict[str, Any]], Any],
                 batch_factory: Callable[[Dict[str, Any]], Any],
                 base_config: Dict[str, Any],
                 tuning_space: Optional[Dict[str, List[Any]]] = None,
                 metric: str = "throughput", warmup_steps: int = 1,
                 timed_steps: int = 3, model_params_count: int = 0,
                 hbm_bytes: int = 0, dp_size: int = 1):
        """``engine_factory(config_dict) -> engine`` builds a fresh engine;
        ``batch_factory(config_dict) -> batch`` supplies a matching global
        batch.  Factories own model/params so the tuner stays generic.

        ``model_params_count`` + ``hbm_bytes`` (both optional) switch on
        the memory model: candidates whose estimated state footprint
        exceeds HBM are pruned WITHOUT compiling them (the reference
        ModelBasedTuner's OOM pre-screen); 0 for either disables it."""
        self.engine_factory = engine_factory
        self.batch_factory = batch_factory
        self.base_config = base_config
        self.space = tuning_space or DEFAULT_TUNING_SPACE
        self.metric = metric
        self.warmup_steps = warmup_steps
        self.timed_steps = timed_steps
        self.model_params_count = int(model_params_count)
        self.hbm_bytes = int(hbm_bytes)
        self.dp_size = max(int(dp_size), 1)
        self.records: List[Dict[str, Any]] = []
        self._mm = None  # one shared memory model — calibrations persist

    def _apply(self, cfg: Dict[str, Any], dotted: str, value: Any) -> None:
        node = cfg
        parts = dotted.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def _candidates(self):
        keys = list(self.space.keys())
        for combo in itertools.product(*(self.space[k] for k in keys)):
            cfg = json.loads(json.dumps(self.base_config))
            for k, v in zip(keys, combo):
                self._apply(cfg, k, v)
            yield dict(zip(keys, combo)), cfg

    def _memory_model(self):
        """The plane's calibrated memory model at legacy semantics:
        margin 0, scale 1 until a trial calibrates it.  ONE instance per
        tuner — every trial's calibration sharpens later prune calls."""
        from ..tuning.memory_model import CalibratedMemoryModel

        if self._mm is None:
            self._mm = CalibratedMemoryModel(
                params_count=self.model_params_count,
                hbm_limit_bytes=self.hbm_bytes, dp_size=self.dp_size,
                base_config=self.base_config, margin_frac=0.0)
        return self._mm

    def _memory_prune(self, combo: Dict[str, Any]) -> bool:
        """True → skip without compiling (estimated state exceeds HBM)."""
        if not (self.model_params_count and self.hbm_bytes):
            return False
        return self._memory_model().prune_reason(combo) is not None

    def _runner(self, base_config: Optional[Dict[str, Any]] = None):
        from ..tuning.trial import EngineTrialRunner

        return EngineTrialRunner(
            self.engine_factory, self.batch_factory,
            base_config if base_config is not None else self.base_config,
            warmup_steps=self.warmup_steps,
            memory_model=self._memory_model()
            if self.model_params_count else None)

    def _measure(self, combo: Dict[str, Any]) -> Optional[float]:
        """One candidate's samples/sec through the tuning plane's trial
        runner: every timed step is DEVICE-FENCED (loss-scalar fetch),
        and engines exposing the ``trial_run`` hook are scored from
        their own StepRecords.  The COMBO (not a pre-merged config) is
        what runs, so ledger calibration sees the candidate's real ZeRO
        stage instead of the base config's."""
        result = self._runner().run(combo, timed_steps=self.timed_steps)
        if not result.feasible:
            logger.warning(
                f"autotuning candidate failed: {result.error}"
                + (" (OOM)" if result.oom else ""))
            return None
        rate = result.score(self.metric if self.metric in result.metrics
                            else "samples_per_sec")
        if rate is None:
            rate = result.score("tokens_per_sec")
        return rate

    def tune(self) -> Dict[str, Any]:
        """Grid search through the tuning plane (``tuning.SearchEngine``
        + ``GridStrategy``), mapped back to the reference result shape
        ``{"best_config", "best_combo", "throughput", "records"}``."""
        from ..tuning.search import GridStrategy, SearchEngine
        from ..tuning.space import CandidateSpace, Dimension

        space = CandidateSpace()
        for name, values in self.space.items():
            space.register(Dimension(name, list(values)))
        metric = (self.metric if self.metric != "throughput"
                  else "samples_per_sec")
        eng = SearchEngine(
            self._runner(), space,
            strategy=GridStrategy(timed_steps=self.timed_steps),
            metric=metric,
            memory_model=self._memory_model()
            if (self.model_params_count and self.hbm_bytes) else None)
        result = eng.search()
        for rec in result.records:
            combo = rec.get("candidate")
            if combo is None:
                continue
            if rec.get("pruned"):
                self.records.append({"combo": combo, "throughput": None,
                                     "pruned": rec["pruned"]})
            else:
                rate = (rec.get("metrics") or {}).get(
                    metric, (rec.get("metrics") or {}).get(
                        "samples_per_sec"))
                self.records.append({"combo": combo, "throughput": rate})
        if result.best is None:
            raise RuntimeError("no autotuning candidate succeeded")
        combo = result.best.candidate
        best_rate = result.best.score(metric) or 0.0
        cfg = json.loads(json.dumps(self.base_config))
        for k, v in combo.items():
            self._apply(cfg, k, v)
        log_dist(f"autotuning best: {combo} at {best_rate:.1f} samples/s")
        return {"best_config": cfg, "best_combo": combo,
                "throughput": best_rate, "records": self.records}

    def write_best(self, path: str) -> None:
        result = self.tune()
        with open(path, "w") as f:
            json.dump(result["best_config"], f, indent=2)


class ModelBasedTuner(Autotuner):
    """Reference ``ModelBasedTuner`` role (SURVEY §2.5, VERDICT r2 missing
    #7): instead of timing the full grid, measure a small SEED set, fit a
    performance model, and spend the remaining measurement budget only on
    the top-predicted candidates.

    The model is additive in log-throughput over the tuning dimensions
    (``log T ≈ base + Σ_dim effect[dim=value]``, one-hot least squares) —
    the same structure the reference fits over micro-batch/stage curves.
    Memory-model pruning applies before anything is measured."""

    def __init__(self, *args, seed_measurements: int = 3,
                 measure_budget: int = 6, **kwargs):
        super().__init__(*args, **kwargs)
        self.seed_measurements = max(2, int(seed_measurements))
        self.measure_budget = max(self.seed_measurements + 1,
                                  int(measure_budget))

    # -- the performance model --------------------------------------------

    @staticmethod
    def _design_row(combo: Dict[str, Any], levels: Dict[str, List[Any]]):
        import numpy as np

        row = [1.0]
        for k, vals in levels.items():
            onehot = [0.0] * len(vals)
            onehot[vals.index(combo[k])] = 1.0
            row.extend(onehot)
        return np.asarray(row)

    def _fit_predict(self, measured, candidates):
        """measured: [(combo, throughput)] → predicted throughput for every
        candidate combo (same additive-log model for all)."""
        import numpy as np

        levels = {k: list(self.space[k]) for k in self.space}
        X = np.stack([self._design_row(c, levels) for c, _ in measured])
        y = np.log([max(t, 1e-9) for _, t in measured])
        coef, *_ = np.linalg.lstsq(X, y, rcond=None)
        return [float(np.exp(self._design_row(c, levels) @ coef))
                for c in candidates]

    def _seed_combos(self, combos):
        """Greedy level cover: every (dimension, level) pair must appear in
        at least one seed, else that level's effect is unidentifiable and
        the model can never rank untried configs containing it."""
        uncovered = {(k, v) for k in self.space for v in self.space[k]}
        idx: List[int] = []
        while uncovered:
            best_i, best_gain = None, -1
            for i, (combo, _) in enumerate(combos):
                if i in idx:
                    continue
                gain = sum((k, combo[k]) in uncovered for k in combo)
                if gain > best_gain:
                    best_i, best_gain = i, gain
            if best_i is None or best_gain <= 0:
                break  # remaining levels were memory-pruned away entirely
            idx.append(best_i)
            uncovered -= {(k, combos[best_i][0][k])
                          for k in combos[best_i][0]}
        # top up to the requested seed count with evenly spaced extras
        step = max(1, len(combos) // max(self.seed_measurements, 1))
        for i in range(0, len(combos), step):
            if len(idx) >= self.seed_measurements:
                break
            if i not in idx:
                idx.append(i)
        return sorted(idx)

    def tune(self) -> Dict[str, Any]:
        all_cands = [(combo, cfg) for combo, cfg in self._candidates()
                     if not self._memory_prune(combo)]
        if not all_cands:
            raise RuntimeError("memory model pruned every candidate")
        measured: List = []

        def run(i: int) -> None:
            combo, cfg = all_cands[i]
            rate = self._measure(combo)
            self.records.append({"combo": combo, "throughput": rate})
            log_dist(f"autotuning(model) {combo} -> "
                     f"{'FAIL' if rate is None else f'{rate:.1f} samples/s'}")
            if rate is not None:
                measured.append((combo, rate, cfg))

        seen = set()
        for i in self._seed_combos(all_cands):
            seen.add(i)
            run(i)
        if not measured:
            raise RuntimeError("no autotuning seed candidate succeeded")

        remaining = [i for i in range(len(all_cands)) if i not in seen]
        if remaining:
            preds = self._fit_predict([(c, t) for c, t, _ in measured],
                                      [all_cands[i][0] for i in remaining])
            ranked = sorted(zip(preds, remaining), reverse=True)
            n_extra = max(0, self.measure_budget - len(seen))
            for _, i in ranked[:n_extra]:
                seen.add(i)
                run(i)
            for pred, i in ranked[n_extra:]:
                self.records.append({"combo": all_cands[i][0],
                                     "throughput": None,
                                     "pruned": "perf_model",
                                     "predicted": pred})

        combo, rate, cfg = max(measured, key=lambda m: m[1])
        log_dist(f"autotuning(model) best: {combo} at {rate:.1f} samples/s "
                 f"({len([r for r in self.records if 'pruned' not in r])} "
                 f"of {len(all_cands)} candidates measured)")
        return {"best_config": cfg, "best_combo": combo, "throughput": rate,
                "records": self.records}


def autotune(engine_factory, batch_factory, base_config,
             tuning_space=None, model_based: bool = False) -> Dict[str, Any]:
    cls = ModelBasedTuner if model_based else Autotuner
    return cls(engine_factory, batch_factory, base_config,
               tuning_space).tune()
