from .autotuner import Autotuner, ModelBasedTuner, autotune

__all__ = ["Autotuner", "ModelBasedTuner", "autotune"]
