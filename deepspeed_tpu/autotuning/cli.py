"""``deepspeed --autotuning`` — launcher-driven autotuning orchestration.

Reference: ``deepspeed/autotuning/autotuner.py`` driven from the launcher
(``deepspeed --autotuning {tune,run} script.py --deepspeed_config ds.json``,
SURVEY §2.5): the launcher runs SHORT PROFILING JOBS of the user's own
script over the tuning space, ranks them by measured throughput, writes the
best config, and (``run`` mode) relaunches the real job with it.

TPU-native mechanics: each candidate is a subprocess of the user script
with two env hooks the runtime honors —
``DS_AUTOTUNING_CONFIG_OVERRIDE`` (dotted-key JSON merged into the DS
config by ``deepspeed_tpu.initialize``) and ``DS_AUTOTUNING_RESULT``
(path where the engine writes measured samples/sec after
``DS_AUTOTUNING_STEPS`` steps, fencing the async dispatch first).  A
candidate that OOMs or crashes simply scores None — XLA raises
synchronously, the reference's job-failure handling collapses to an exit
code.  The in-process ``autotuning.autotuner`` (grid/model-based tuners)
remains the API surface; this module is the CLI deployment of the same
search."""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import logger
from .autotuner import DEFAULT_TUNING_SPACE, OFFLOAD_TUNING_SPACE


def _tuning_space(args) -> Dict[str, List[Any]]:
    env_space = os.environ.get("DS_AUTOTUNING_SPACE")
    if env_space:
        return json.loads(env_space)
    if getattr(args, "autotuning_space", "") == "offload":
        return dict(OFFLOAD_TUNING_SPACE)
    return dict(DEFAULT_TUNING_SPACE)


def orchestrate(args, cmd: List[str]) -> int:
    """Run the tuning loop; ``run`` mode then launches the real job with
    the winning config override in the environment."""
    space = _tuning_space(args)
    keys = list(space.keys())
    combos = [dict(zip(keys, vals))
              for vals in itertools.product(*(space[k] for k in keys))]
    results_dir = os.path.abspath(
        getattr(args, "autotuning_results", "") or "autotuning_results")
    os.makedirs(results_dir, exist_ok=True)
    steps = os.environ.get("DS_AUTOTUNING_STEPS", "8")
    budget_s = float(os.environ.get("DS_AUTOTUNING_JOB_TIMEOUT_S", "300"))

    scored: List[Dict[str, Any]] = []
    for i, combo in enumerate(combos):
        result_path = os.path.join(results_dir, f"candidate_{i}.json")
        if os.path.exists(result_path):
            os.unlink(result_path)
        env = dict(os.environ)
        env.update({
            "DS_AUTOTUNING_CONFIG_OVERRIDE": json.dumps(combo),
            "DS_AUTOTUNING_RESULT": result_path,
            "DS_AUTOTUNING_STEPS": steps,
        })
        t0 = time.time()
        proc = subprocess.Popen(cmd, env=env)
        tput: Optional[float] = None
        try:
            # reap on result-file appearance OR process end OR budget —
            # a profiling candidate must never hold the tuning loop
            while time.time() - t0 < budget_s:
                if os.path.exists(result_path):
                    # the engine writes tmp+rename (atomic), but stay
                    # defensive: an unreadable file is retried next tick
                    try:
                        with open(result_path) as f:
                            tput = json.load(f).get("samples_per_sec")
                        break
                    except (json.JSONDecodeError, OSError):
                        pass
                if proc.poll() is not None:
                    break
                time.sleep(0.5)
        finally:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        scored.append({"config": combo, "samples_per_sec": tput,
                       "rc": proc.returncode})
        logger.info(f"autotuning candidate {i + 1}/{len(combos)} "
                    f"{combo} -> {tput if tput is not None else 'FAILED'}")

    ok = [s for s in scored if s["samples_per_sec"] is not None]
    summary_path = os.path.join(results_dir, "autotuning_summary.json")
    with open(summary_path, "w") as f:
        json.dump(scored, f, indent=2)
    if not ok:
        logger.error("autotuning: every candidate failed "
                     f"(see {summary_path})")
        return 1
    best = max(ok, key=lambda s: s["samples_per_sec"])
    best_path = os.path.join(results_dir, "best_config.json")
    with open(best_path, "w") as f:
        json.dump(best["config"], f, indent=2)
    logger.info(f"autotuning: best {best['config']} "
                f"({best['samples_per_sec']:.1f} samples/sec) -> "
                f"{best_path}")

    if args.autotuning == "run":
        # hand the winner to the caller's NORMAL launch path via the
        # environment (runner.py falls through to hostfile/launcher
        # machinery after this returns 0)
        os.environ["DS_AUTOTUNING_CONFIG_OVERRIDE"] = json.dumps(
            best["config"])
        os.environ.pop("DS_AUTOTUNING_RESULT", None)
    return 0
