"""Abstract accelerator interface.

Reference: ``deepspeed/accelerator/abstract_accelerator.py`` [K] — its
~90-method surface mapped onto XLA semantics: device/memory/RNG queries
answer through jax; CUDA stream/event micromanagement collapses to
ordered-dispatch no-op objects (Events still time via host clocks, the
use DeepSpeed's timers put them to); ``*Tensor`` constructors build jnp
arrays; profiler ranges map to ``jax.named_scope``.  Methods the reference needs only for CUDA stream/event
micromanagement collapse to no-ops under XLA's async dispatch model and
are still present so accelerator-generic caller code ports unchanged.
"""

from __future__ import annotations

import abc
from typing import Any, Optional


class DeepSpeedAccelerator(abc.ABC):
    _name: str = "abstract"
    _communication_backend_name: str = "none"

    # -- identity ----------------------------------------------------------

    def device_name(self, device_index: Optional[int] = None) -> str:
        return (self._name if device_index is None
                else f"{self._name}:{device_index}")

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    @abc.abstractmethod
    def current_device(self) -> int: ...

    @abc.abstractmethod
    def device_count(self) -> int: ...

    @abc.abstractmethod
    def is_available(self) -> bool: ...

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    # -- capabilities ------------------------------------------------------

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def supported_dtypes(self) -> list:
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16]

    # -- device handles / execution ---------------------------------------

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None) -> Any: ...

    def synchronize(self, device_index: Optional[int] = None) -> None:
        """Drain outstanding work (XLA: block on a trivial computation)."""
        import jax
        import jax.numpy as jnp

        jnp.zeros(()).block_until_ready()
        jax.effects_barrier()

    # streams/events: XLA schedules async itself; kept as no-op objects
    class _NullStream:
        def __enter__(self):  # pragma: no cover - trivial
            return self

        def __exit__(self, *a):
            return False

        def synchronize(self):
            pass

    def Stream(self, *a, **k):
        return self._NullStream()

    def stream(self, s):
        return self._NullStream()

    def current_stream(self, device_index=None):
        return self._NullStream()

    def default_stream(self, device_index=None):
        return self._NullStream()

    # -- memory ------------------------------------------------------------

    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> dict: ...

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        return self.total_memory(device_index) - self.memory_allocated(
            device_index)

    def empty_cache(self) -> None:
        pass

    def pin_memory(self, tensor: Any, align_bytes: int = 1) -> Any:
        return tensor  # host numpy is already DMA-able through dlpack

    # -- RNG ---------------------------------------------------------------

    def manual_seed(self, seed: int) -> None:
        self._seed = int(seed)

    def initial_seed(self) -> int:
        return getattr(self, "_seed", 0)

    # -- op builders -------------------------------------------------------

    def create_op_builder(self, class_name: str) -> Any:
        builder = self.get_op_builder(class_name)
        return builder() if builder is not None else None

    def get_op_builder(self, class_name: str) -> Any:
        from ..ops.op_builder.builder import get_op_builder

        return get_op_builder(class_name)

    # -- events ------------------------------------------------------------
    # XLA's dispatch is ordered per device; an Event reduces to a marker
    # that can synchronize (drain) and report elapsed wall time between
    # two recorded points — the uses DeepSpeed's timers put them to.

    class _Event:
        def __init__(self, enable_timing: bool = False):
            self._t = None
            self._timing = enable_timing

        @staticmethod
        def _drain():
            # XLA dispatch is async: a host timestamp taken without
            # draining outstanding device work measures dispatch latency,
            # not execution.  FETCH a freshly computed scalar (device
            # round-trip through execution) rather than block_until_ready
            # — on the tunneled platform block_until_ready returns
            # immediately (see bench.py block()), while a device_get
            # completes only after this program executes, which per-device
            # in-order execution sequences after all previously dispatched
            # work.  An Event holds no handle on that work, so the
            # in-order-execution assumption (true of XLA's per-device
            # streams) is what makes this independent fetch a fence.
            try:
                import jax
                import jax.numpy as jnp
                import numpy as _np

                _np.asarray(jnp.zeros(()) + 1.0)
                jax.effects_barrier()
            except Exception as e:
                # no device / not initialized — host-only semantics
                from ..utils.logging import debug_once

                debug_once("accelerator/event_drain",
                           f"Event drain skipped ({e!r}); "
                           f"host-only timing semantics")

        def record(self, stream=None):
            import time as _time

            if self._timing:
                self._drain()
            self._t = _time.perf_counter()

        def synchronize(self):
            self._drain()

        def query(self) -> bool:
            return True

        def elapsed_time(self, other) -> float:
            """Milliseconds from self.record() to other.record().

            Like ``torch.cuda.Event``, raises unless BOTH events were
            created with ``enable_timing=True`` — un-timed records don't
            drain async dispatch, so their stamps measure dispatch
            latency and would be confidently wrong."""
            if not (self._timing and getattr(other, "_timing", False)):
                raise RuntimeError(
                    "elapsed_time requires both events to be created "
                    "with enable_timing=True")
            if self._t is None or getattr(other, "_t", None) is None:
                return 0.0
            return (other._t - self._t) * 1e3

    def Event(self, enable_timing: bool = False):
        return self._Event(enable_timing)

    # -- execution-model queries (reference capability probes) -------------

    def is_synchronized_device(self) -> bool:
        return False  # XLA dispatch is async

    def use_host_timers(self) -> bool:
        # no CUDA-event timers; device timing comes from profiler traces
        return True

    def resolves_data_dependency(self) -> bool:
        return True  # XLA orders by data dependence, not stream order

    def handles_memory_backpressure(self) -> bool:
        return False

    def set_device(self, device_index: int) -> None:
        # one process drives all local chips under jax; per-device placement
        # is explicit via shardings, so this is bookkeeping only
        self._current_device = int(device_index)

    def device_properties(self, device_index: Optional[int] = None) -> dict:
        d = self.device(device_index)
        props = {"name": getattr(d, "device_kind", self._name),
                 "platform": getattr(d, "platform", self._name),
                 "id": getattr(d, "id", device_index or 0)}
        props["total_memory"] = self.total_memory(device_index)
        return props

    def get_device_name(self, device_index: Optional[int] = None) -> str:
        return str(self.device_properties(device_index)["name"])

    # -- memory (peak tracking + reference aliases) ------------------------

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get(
            "peak_bytes_in_use", self.memory_allocated(device_index)))

    def reset_peak_memory_stats(self, device_index=None) -> None:
        pass  # XLA exposes a monotone peak; nothing to reset

    def memory_reserved(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get(
            "bytes_reserved", self.memory_allocated(device_index)))

    def max_memory_reserved(self, device_index: Optional[int] = None) -> int:
        return self.max_memory_allocated(device_index)

    def memory_cached(self, device_index: Optional[int] = None) -> int:
        return self.memory_reserved(device_index)

    def max_memory_cached(self, device_index: Optional[int] = None) -> int:
        return self.max_memory_reserved(device_index)

    def mem_get_info(self, device_index: Optional[int] = None) -> tuple:
        total = self.total_memory(device_index)
        return (total - self.memory_allocated(device_index), total)

    def is_pinned(self, tensor: Any) -> bool:
        return True  # host numpy is DMA-able as-is

    # -- RNG (jax is explicit-key; these serve compat callers) -------------

    def random(self):
        import jax

        return jax.random

    def default_generator(self, device_index: Optional[int] = None):
        import jax

        return jax.random.PRNGKey(self.initial_seed())

    def manual_seed_all(self, seed: int) -> None:
        self.manual_seed(seed)

    # -- profiler range markers (reference nvtx surface) -------------------

    def range_push(self, msg: str):
        import jax

        scope = jax.named_scope(msg)
        scope.__enter__()
        self._scopes = getattr(self, "_scopes", [])
        self._scopes.append(scope)

    def range_pop(self):
        scopes = getattr(self, "_scopes", [])
        if scopes:
            scopes.pop().__exit__(None, None, None)

    def lazy_call(self, callback) -> None:
        callback()  # no CUDA-context laziness to defer around

    # -- dtype/tensor helpers (reference *Tensor constructors) -------------

    def BFloat16Tensor(self, data):
        import jax.numpy as jnp

        return jnp.asarray(data, dtype=jnp.bfloat16)

    def FloatTensor(self, data):
        import jax.numpy as jnp

        return jnp.asarray(data, dtype=jnp.float32)

    def HalfTensor(self, data):
        import jax.numpy as jnp

        return jnp.asarray(data, dtype=jnp.float16)

    def IntTensor(self, data):
        import jax.numpy as jnp

        return jnp.asarray(data, dtype=jnp.int32)

    def LongTensor(self, data):
        import jax.numpy as jnp

        return jnp.asarray(data, dtype=jnp.int64)

    def ByteTensor(self, data):
        import jax.numpy as jnp

        return jnp.asarray(data, dtype=jnp.uint8)

    # -- visibility / env --------------------------------------------------

    def visible_devices_envs(self) -> list:
        return ["TPU_VISIBLE_DEVICES", "JAX_PLATFORMS"]

    def set_visible_devices_envs(self, current_env: dict,
                                 local_accelerator_ids: list) -> None:
        current_env["TPU_VISIBLE_DEVICES"] = ",".join(
            str(i) for i in local_accelerator_ids)

    def export_envs(self) -> list:
        return ["TPU", "JAX", "XLA", "LIBTPU"]

    def is_triton_supported(self) -> bool:
        return False  # pallas is the kernel story

    def build_extension(self):
        from ..ops.op_builder import builder

        return builder

