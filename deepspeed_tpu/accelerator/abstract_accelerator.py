"""Abstract accelerator interface.

Reference: ``deepspeed/accelerator/abstract_accelerator.py`` [K] — the
subset of its ~90 methods that the TPU runtime actually dispatches
through.  Methods the reference needs only for CUDA stream/event
micromanagement collapse to no-ops under XLA's async dispatch model and
are still present so accelerator-generic caller code ports unchanged.
"""

from __future__ import annotations

import abc
from typing import Any, Optional


class DeepSpeedAccelerator(abc.ABC):
    _name: str = "abstract"
    _communication_backend_name: str = "none"

    # -- identity ----------------------------------------------------------

    def device_name(self, device_index: Optional[int] = None) -> str:
        return (self._name if device_index is None
                else f"{self._name}:{device_index}")

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    @abc.abstractmethod
    def current_device(self) -> int: ...

    @abc.abstractmethod
    def device_count(self) -> int: ...

    @abc.abstractmethod
    def is_available(self) -> bool: ...

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    # -- capabilities ------------------------------------------------------

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def supported_dtypes(self) -> list:
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16]

    # -- device handles / execution ---------------------------------------

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None) -> Any: ...

    def synchronize(self, device_index: Optional[int] = None) -> None:
        """Drain outstanding work (XLA: block on a trivial computation)."""
        import jax
        import jax.numpy as jnp

        jnp.zeros(()).block_until_ready()
        jax.effects_barrier()

    # streams/events: XLA schedules async itself; kept as no-op objects
    class _NullStream:
        def __enter__(self):  # pragma: no cover - trivial
            return self

        def __exit__(self, *a):
            return False

        def synchronize(self):
            pass

    def Stream(self, *a, **k):
        return self._NullStream()

    def stream(self, s):
        return self._NullStream()

    def current_stream(self, device_index=None):
        return self._NullStream()

    def default_stream(self, device_index=None):
        return self._NullStream()

    # -- memory ------------------------------------------------------------

    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> dict: ...

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        return self.total_memory(device_index) - self.memory_allocated(
            device_index)

    def empty_cache(self) -> None:
        pass

    def pin_memory(self, tensor: Any, align_bytes: int = 1) -> Any:
        return tensor  # host numpy is already DMA-able through dlpack

    # -- RNG ---------------------------------------------------------------

    def manual_seed(self, seed: int) -> None:
        self._seed = int(seed)

    def initial_seed(self) -> int:
        return getattr(self, "_seed", 0)

    # -- op builders -------------------------------------------------------

    def create_op_builder(self, class_name: str) -> Any:
        builder = self.get_op_builder(class_name)
        return builder() if builder is not None else None

    def get_op_builder(self, class_name: str) -> Any:
        from ..ops.op_builder.builder import get_op_builder

        return get_op_builder(class_name)
