"""Accelerator abstraction — the reference's L0 extension point.

Reference: ``deepspeed/accelerator/`` [K] (SURVEY §1 L0):
``abstract_accelerator.py:DeepSpeedAccelerator`` (~90 abstract methods) +
``real_accelerator.py:get_accelerator()`` auto-detecting singleton with the
``DS_ACCELERATOR`` env override.  The north star names a ``tpu`` accelerator
as the sanctioned extension path [D BASELINE.json].
"""

from .abstract_accelerator import DeepSpeedAccelerator
from .real_accelerator import get_accelerator, set_accelerator
from .tpu_accelerator import CPU_Accelerator, TPU_Accelerator

__all__ = ["DeepSpeedAccelerator", "get_accelerator", "set_accelerator",
           "TPU_Accelerator", "CPU_Accelerator"]
