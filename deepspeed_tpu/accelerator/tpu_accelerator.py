"""TPU (and CPU-fallback) accelerator implementations.

Reference roles: ``deepspeed/accelerator/cuda_accelerator.py`` /
``cpu_accelerator.py`` [K].  The TPU class answers through jax/libtpu;
the CPU class serves the virtual-mesh test environment.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):
    _name = "tpu"
    #: collectives ride XLA over ICI/DCN — the backend jax.distributed sets up
    _communication_backend_name = "xla"

    def _devices(self):
        return [d for d in jax.devices() if d.platform == "tpu"]

    def is_available(self) -> bool:
        try:
            return len(self._devices()) > 0
        except Exception:
            return False

    def current_device(self) -> int:
        return 0  # one process drives all local chips under jax

    def device_count(self) -> int:
        return len(self._devices())

    def device(self, device_index: Optional[int] = None) -> Any:
        return self._devices()[device_index or 0]

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        try:
            return dict(self.device(device_index).memory_stats() or {})
        except Exception:
            return {}

    def device_kind(self) -> str:
        return self.device().device_kind

    def on_accelerator(self, tensor: Any) -> bool:
        sharding = getattr(tensor, "sharding", None)
        if sharding is None:
            return False
        return any(d.platform == "tpu" for d in sharding.device_set)


class CPU_Accelerator(DeepSpeedAccelerator):
    _name = "cpu"
    _communication_backend_name = "gloo"  # reference name for the CPU path

    def is_available(self) -> bool:
        return True

    def current_device(self) -> int:
        return 0

    def device_count(self) -> int:
        return len([d for d in jax.devices() if d.platform == "cpu"]) or 1

    def device(self, device_index: Optional[int] = None) -> Any:
        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        return cpus[device_index or 0] if cpus else jax.devices()[0]

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        return {}

    def on_accelerator(self, tensor: Any) -> bool:
        return True
