"""get_accelerator() — auto-detecting singleton with env override.

Reference: ``deepspeed/accelerator/real_accelerator.py`` [K]:
``get_accelerator()`` probes hardware once and caches; ``DS_ACCELERATOR``
env forces a backend; ``set_accelerator()`` installs a custom one (the
sanctioned extension path the north star names for new hardware).
"""

from __future__ import annotations

import os
from typing import Optional

from .abstract_accelerator import DeepSpeedAccelerator

_ACCELERATOR: Optional[DeepSpeedAccelerator] = None


def get_accelerator() -> DeepSpeedAccelerator:
    global _ACCELERATOR
    if _ACCELERATOR is not None:
        return _ACCELERATOR
    from .tpu_accelerator import CPU_Accelerator, TPU_Accelerator

    forced = os.environ.get("DS_ACCELERATOR", "").lower()
    if forced == "cpu":
        _ACCELERATOR = CPU_Accelerator()
    elif forced == "tpu":
        _ACCELERATOR = TPU_Accelerator()
    elif forced:
        raise ValueError(f"DS_ACCELERATOR={forced!r} is not a known "
                         "accelerator (tpu, cpu)")
    else:
        tpu = TPU_Accelerator()
        _ACCELERATOR = tpu if tpu.is_available() else CPU_Accelerator()
    return _ACCELERATOR


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator()._name in ("tpu", "cpu")
